"""tools/bench_retry.py: the retry/timeout/backoff harness must emit a
structured, machine-readable record for every failure mode — wedged chip,
absent chip, failing bench, healthy run — instead of a bare null."""

import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

from bench_retry import run_with_retries  # noqa: E402


def _probe_ok(timeout_s=60):
    return True, "BACKEND_OK fake 1"


def _probe_wedged(timeout_s=60):
    return False, f"timeout after {timeout_s}s (chip unreachable/wedged)"


def _probe_absent(timeout_s=60):
    return False, "probe rc=1: ModuleNotFoundError: no accelerator plugin"


def test_ok_run_forwards_result_json():
    cmd = [sys.executable, "-c",
           "import json; print('noise'); "
           "print(json.dumps({'metric': 'm', 'value': 1.5}))"]
    rec = run_with_retries(cmd, attempts=2, timeout_s=30, backoff_s=0.0,
                           probe_fn=_probe_ok)
    assert rec["classification"] == "ok"
    assert rec["result"] == {"metric": "m", "value": 1.5}
    assert rec["probe_count"] == 1
    assert rec["attempts"][0]["ok"] is True
    json.dumps(rec)  # the whole record must be JSON-serializable.


def test_wedged_chip_classified_and_counted():
    rec = run_with_retries([sys.executable, "-c", "pass"], attempts=3, timeout_s=5, backoff_s=0.0,
                           probe_fn=_probe_wedged)
    assert rec["classification"] == "wedged"
    assert rec["probe_count"] == 3  # kept retrying: wedged may recover.
    assert "timeout" in rec["last_error"]
    assert len(rec["attempts"]) == 3
    json.dumps(rec)


def test_absent_chip_fails_fast():
    rec = run_with_retries([sys.executable, "-c", "pass"], attempts=5, timeout_s=5, backoff_s=0.0,
                           probe_fn=_probe_absent)
    assert rec["classification"] == "absent"
    assert rec["probe_count"] == 1  # no chip to wait for: no retries.
    assert "plugin" in rec["last_error"]
    json.dumps(rec)


def test_failing_bench_records_stderr_tail():
    cmd = [sys.executable, "-c",
           "import sys; print('boom-detail', file=sys.stderr); sys.exit(3)"]
    rec = run_with_retries(cmd, attempts=2, timeout_s=30, backoff_s=0.0,
                           probe_fn=_probe_ok)
    assert rec["classification"] == "failed"
    assert rec["probe_count"] == 2
    assert "rc=3" in rec["last_error"]
    assert "boom-detail" in rec["last_error"]
    json.dumps(rec)


def test_hung_bench_classified_wedged():
    cmd = [sys.executable, "-c", "import time; time.sleep(60)"]
    rec = run_with_retries(cmd, attempts=1, timeout_s=1, backoff_s=0.0,
                           probe_fn=_probe_ok)
    assert rec["classification"] == "wedged"
    assert "timed out" in rec["last_error"]
    json.dumps(rec)


def test_fast_failure_mentioning_timeout_is_still_absent():
    """Classification keys on probe()'s structured 'timeout after' prefix,
    not a substring: a fast rc!=0 failure whose stderr mentions a timeout
    (e.g. an rpc DEADLINE_EXCEEDED) is an ABSENT chip — retrying with
    backoff cannot help."""
    def probe_rpc(timeout_s=60):
        return False, "probe rc=1: DEADLINE_EXCEEDED: rpc timeout"

    rec = run_with_retries([sys.executable, "-c", "pass"], attempts=5,
                           timeout_s=5, backoff_s=0.0, probe_fn=probe_rpc)
    assert rec["classification"] == "absent"
    assert rec["probe_count"] == 1


def test_wedged_run_emits_structured_backend_unavailable_result():
    """S6 null-record fix: a wedged/absent round's ``result`` is a
    structured backend_unavailable record (not null), distinguishable
    from a genuine regression by downstream tooling."""
    rec = run_with_retries([sys.executable, "-c", "pass"], attempts=2,
                           timeout_s=5, backoff_s=0.0,
                           probe_fn=_probe_wedged)
    assert rec["backend_unavailable"] is True
    assert rec["result"]["status"] == "backend_unavailable"
    assert rec["result"]["classification"] == "wedged"
    assert rec["result"]["value"] is None
    json.dumps(rec)
    rec = run_with_retries([sys.executable, "-c", "pass"], attempts=2,
                           timeout_s=5, backoff_s=0.0,
                           probe_fn=_probe_absent)
    assert rec["result"]["classification"] == "absent"


def test_failed_bench_keeps_null_result():
    """A bench-side failure (rc != 0 with a live chip) is a CODE problem:
    result stays null and no backend_unavailable tag appears."""
    rec = run_with_retries([sys.executable, "-c", "import sys; sys.exit(2)"],
                           attempts=1, timeout_s=30, backoff_s=0.0,
                           probe_fn=_probe_ok)
    assert rec["classification"] == "failed"
    assert rec["result"] is None
    assert "backend_unavailable" not in rec


def test_sweep_retry_resumes_from_journal(tmp_path):
    """A --sweep attempt that dies after journaling cells is retried WITH
    --resume (continue from the journal, not from zero); the record carries
    resumed_from_chunk and forwards the bench's final JSON line."""
    journal = tmp_path / "BENCH_SWEEP_JOURNAL.jsonl"
    journal.write_text(
        json.dumps({"event": "run_start", "git_head": "abc"}) + "\n"
        + json.dumps({"event": "cell", "cell": "a", "value": 1}) + "\n"
        + json.dumps({"event": "cell", "cell": "b", "value": 2}) + "\n"
        + '{"event": "cell", "cel'  # torn tail from the crash.
    )
    script = (
        "import json, sys\n"
        "if '--resume' not in sys.argv: sys.exit(1)\n"
        "print(json.dumps({'metric': 'bench_sweep',"
        " 'resumed_from_chunk': 2}))\n"
    )
    cmd = [sys.executable, "-c", script, "--sweep"]
    rec = run_with_retries(cmd, attempts=2, timeout_s=30, backoff_s=0.0,
                           probe_fn=_probe_ok, cwd=str(tmp_path))
    assert rec["classification"] == "ok"
    assert rec["resumed_from_chunk"] == 2  # torn third cell not counted.
    assert rec["attempts"][1]["resumed"] is True
    assert rec["result"]["resumed_from_chunk"] == 2
    json.dumps(rec)


def test_non_sweep_retry_never_appends_resume(tmp_path):
    """--resume is a sweep-journal contract; headline runs must retry with
    the original command even when a journal file happens to exist."""
    (tmp_path / "BENCH_SWEEP_JOURNAL.jsonl").write_text(
        json.dumps({"event": "cell", "cell": "a", "value": 1}) + "\n"
    )
    script = (
        "import json, sys\n"
        "if '--resume' in sys.argv: sys.exit(3)\n"
        "sys.exit(1) if len(sys.argv) < 99 else None\n"
    )
    rec = run_with_retries([sys.executable, "-c", script], attempts=2,
                           timeout_s=30, backoff_s=0.0, probe_fn=_probe_ok,
                           cwd=str(tmp_path))
    assert rec["classification"] == "failed"
    assert "resumed_from_chunk" not in rec
    assert all("resumed" not in a for a in rec["attempts"])
