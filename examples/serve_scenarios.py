#!/usr/bin/env python
"""Scenario-serving demo + acceptance driver: a mixed-shape request
stream through the continuous-batching tier (``tpu_aerial_transport/
serving/``).

Generates a deterministic (seeded) stream of heterogeneous
:class:`ScenarioRequest`s — mixed families (controllers), horizons,
initial conditions, deadlines — feeds them to a
:class:`ScenarioServer` on a Poisson arrival clock, and reports
per-request outcomes + SLO stats as JSON. Doubles as the PR's
end-to-end proofs:

- ``--bundle DIR --require-bundle --expect-zero-compile``: the fresh
  process serves the whole stream with 0 traces / 0 MLIR lowerings /
  0 XLA backend compiles (counted like tools/aot_bundle.py serve; exit 3
  otherwise) — requests admit through ``aot.serve_entry``'s exec rung
  and even the template carries come from the bundle's ``args_sample``.
- ``--run-dir D`` + SIGTERM (or ``--sigterm-after N`` for tests):
  preemption completes at the chunk boundary, journals the remainder,
  and a second invocation with ``--resume`` completes it — per-request
  result digests (``--results``) are bit-identical to an uninterrupted
  run.

Usage:
  python examples/serve_scenarios.py --requests 64 --buckets 8,16,32
  python examples/serve_scenarios.py --bundle artifacts/aot/serving-cpu \\
      --require-bundle --expect-zero-compile
  python examples/serve_scenarios.py --run-dir /tmp/serve --resume
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _counters():
    """Whole-process trace/lowering/compile counters via jax monitoring
    (same events as tools/aot_bundle.py serve). Must register before
    anything can compile."""
    from jax._src import monitoring

    counts = {"traces": 0, "lowerings": 0, "backend_compiles": 0}

    def on_duration(event, duration, **kw):
        del duration, kw
        if event.endswith("jaxpr_trace_duration"):
            counts["traces"] += 1
        elif event.endswith("jaxpr_to_mlir_module_duration"):
            counts["lowerings"] += 1
        elif event.endswith("backend_compile_duration"):
            counts["backend_compiles"] += 1

    monitoring.register_event_duration_secs_listener(on_duration)
    return counts


def make_stream(n_requests: int, families: list[str], chunk_lens: dict,
                seed: int, deadline_s: float | None):
    """Deterministic mixed request stream: same seed => same stream, so
    an interrupted+resumed run and an uninterrupted one serve identical
    work (the bit-identity comparison's precondition)."""
    import numpy as np

    from tpu_aerial_transport.serving.queue import ScenarioRequest

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_requests):
        fam = families[int(rng.integers(len(families)))]
        horizon = int(rng.integers(1, 4)) * chunk_lens[fam]
        out.append(ScenarioRequest(
            family=fam, horizon=horizon,
            x0=tuple(float(v) for v in rng.normal(0, 1.0, 3)),
            v0=(0.1, 0.0, 0.0),
            deadline_s=deadline_s,
            request_id=f"req{i:05d}",
        ))
    return out


def result_digest(result) -> str:
    """sha256 over the result pytree's leaf bytes (+ shape/dtype): the
    cross-process bit-identity token."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(result):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--families", default="cadmm4,centralized4")
    ap.add_argument("--buckets", default="8,16,32")
    ap.add_argument("--capacity", type=int, default=512)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--poisson-rate", type=float, default=0.0,
                    help="mean arrivals/s (0 = submit everything up "
                         "front); late arrivals join at chunk boundaries")
    ap.add_argument("--waves", type=int, default=1,
                    help="submit the stream in N deterministic bursts: a "
                         "big first wave (oversubscribes the largest "
                         "bucket, so the overflow joins the running batch "
                         "at chunk boundaries) then geometrically smaller "
                         "idle-separated waves (fresh launches on the "
                         "smaller shape buckets) — the wall-clock-free "
                         "twin of --poisson-rate")
    ap.add_argument("--waves-spec", default="",
                    help="explicit comma-separated wave sizes (overrides "
                         "--waves); must sum to <= --requests")
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--surgery", default="",
                    help="boundary lane-surgery impl: host|device "
                         "(default: resolver — TAT_SERVING_SURGERY else "
                         "host)")
    ap.add_argument("--dispatch", default="",
                    help="chunk dispatch mode: sync|pipelined (pipelined "
                         "double-buffers chunk k+1 and forces device "
                         "surgery)")
    ap.add_argument("--cache", type=int, default=0,
                    help="content-addressed result cache size (0 = off); "
                         "repeat submits of an identical request resolve "
                         "without a dispatch")
    ap.add_argument("--bundle", default="")
    ap.add_argument("--require-bundle", action="store_true")
    ap.add_argument("--expect-zero-compile", action="store_true",
                    help="exit 3 unless traces == lowerings == "
                         "backend_compiles == 0")
    ap.add_argument("--run-dir", default="")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--metrics", default="")
    ap.add_argument("--trace", default="",
                    help="write a Chrome/Perfetto trace of the stream "
                         "(request/queue/batch/device/guard spans + "
                         "critical-path accounting) to this path; load "
                         "it at ui.perfetto.dev")
    ap.add_argument("--results", default="",
                    help="write per-request {id: {status, digest}} JSON")
    ap.add_argument("--sigterm-after", type=int, default=0,
                    help="test hook: raise SIGTERM in-process after N "
                         "pump rounds (graceful boundary preemption)")
    args = ap.parse_args(argv)

    counts = _counters()  # before anything can compile.

    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from tpu_aerial_transport.resilience.recovery import GracefulInterrupt
    from tpu_aerial_transport.serving import batcher, server as server_mod

    t0 = time.perf_counter()
    family_names = [f for f in args.families.split(",") if f]
    buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    chunk_lens = {
        name: batcher.CANONICAL_FAMILIES[name].chunk_len
        for name in family_names
    }
    tracer = None
    if args.trace:
        from tpu_aerial_transport.obs import export as export_mod
        from tpu_aerial_transport.obs import trace as trace_lib

        # Spans also land as trace_event rows in the metrics jsonl when
        # one is configured (one durable record, two renderings).
        sink = (export_mod.MetricsWriter(args.metrics)
                if args.metrics else None)
        tracer = trace_lib.Tracer(sink, track="server")
    kw = dict(
        families=family_names, buckets=buckets, capacity=args.capacity,
        bundle=args.bundle or None, require_bundle=args.require_bundle,
        run_dir=args.run_dir or None,
        metrics=(tracer.sink if tracer is not None and tracer.sink
                 else args.metrics or None),
        tracer=tracer,
        surgery=args.surgery or None, dispatch=args.dispatch or None,
        cache=(args.cache or None),
    )

    with GracefulInterrupt() as interrupt:
        if args.resume:
            server = server_mod.ScenarioServer.resume(
                args.run_dir, **{k: v for k, v in kw.items()
                                 if k != "run_dir"},
            )
            server.interrupt = interrupt
            # Replay the (seed-deterministic) stream spec, deduped
            # against the journal: requests the preempted run never got
            # to submit are served now; restored/completed ones are not
            # resubmitted.
            stream = [
                r for r in make_stream(args.requests, family_names,
                                       chunk_lens, args.seed,
                                       args.deadline_s)
                if r.request_id not in server.tickets
                and r.request_id not in server.done_requests
            ]
        else:
            server = server_mod.ScenarioServer(interrupt=interrupt, **kw)
            stream = make_stream(args.requests, family_names, chunk_lens,
                                 args.seed, args.deadline_s)

        rng_wait = (1.0 / args.poisson_rate) if args.poisson_rate else 0.0
        import numpy as np

        arrival_rng = np.random.default_rng(args.seed + 1)
        next_due = t0
        # Wave sizes: a big first wave (3/4 of the stream — oversubscribes
        # the largest bucket so the overflow late-joins at boundaries)
        # then geometrically smaller idle-separated waves (fresh launches
        # on the smaller shape buckets).
        wave_sizes = []
        if args.resume:
            pass  # replayed tail submits up front; batching already done.
        elif args.waves_spec and stream:
            wave_sizes = [int(w) for w in args.waves_spec.split(",") if w]
            if sum(wave_sizes) > len(stream):
                raise SystemExit("--waves-spec sums past --requests")
            wave_sizes[-1] += len(stream) - sum(wave_sizes)
        elif args.waves > 1 and stream:
            left = len(stream)
            first = max(1, (3 * left) // 4)
            wave_sizes.append(first)
            left -= first
            for w in range(args.waves - 1):
                take = ((left + 1) // 2 if w < args.waves - 2 else left)
                if take:
                    wave_sizes.append(take)
                left -= take
        rounds = 0
        while stream or server.has_work():
            if wave_sizes:
                # Waves land when the server drains — each wave gets its
                # own launch (and therefore its own shape bucket).
                if not server.has_work() and stream:
                    for _ in range(wave_sizes.pop(0)):
                        server.submit(stream.pop(0))
            else:
                while stream and (not rng_wait
                                  or time.perf_counter() >= next_due):
                    server.submit(stream.pop(0))
                    if rng_wait:
                        next_due += arrival_rng.exponential(rng_wait)
            more = server.pump()
            rounds += 1
            if args.sigterm_after and rounds == args.sigterm_after:
                os.kill(os.getpid(), 15)  # handled by GracefulInterrupt.
            if server.preempted:
                break
            if not more and stream and rng_wait:
                # Idle gap before the next Poisson arrival.
                time.sleep(min(0.01, rng_wait))

    wall_s = time.perf_counter() - t0
    stats = server.stats()
    results = {
        rid: {
            "status": t.status,
            **({"reason": t.reason} if t.reason else {}),
            **({"digest": result_digest(t.result)}
               if t.result is not None else {}),
        }
        for rid, t in sorted(server.tickets.items())
    }
    if args.results:
        with open(args.results, "w") as fh:
            json.dump(results, fh, indent=1)
    trace_summary = {}
    if tracer is not None and tracer.rows:
        from tpu_aerial_transport.obs import trace as trace_lib

        trace_lib.write_chrome_trace(
            args.trace, trace_lib.stitch(tracer.rows)
        )
        cp = trace_lib.critical_path(tracer.rows)
        trace_summary = {
            "trace": args.trace,
            "trace_spans": len(tracer.rows),
            "critical_path_p99": {
                seg: round(st["p99"], 4)
                for seg, st in cp["per_segment"].items()
            },
        }
    summary = {
        "mode": ("resume" if args.resume
                 else "bundled" if args.bundle else "jit"),
        "wall_s": round(wall_s, 3),
        "rounds": rounds,
        **trace_summary,
        "scenario_mpc_steps_per_sec": (
            round(stats["scenario_steps"] / wall_s, 2) if wall_s else None
        ),
        **stats,
        **counts,
    }
    print(json.dumps(summary), flush=True)
    if args.expect_zero_compile:
        paid = {k: v for k, v in counts.items() if v}
        if paid:
            print(f"serve_scenarios: NOT zero-compile: {paid}",
                  file=sys.stderr)
            return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
