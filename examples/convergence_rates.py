"""DD vs C-ADMM convergence-rate comparison.

TPU-native counterpart of the reference's disabled-by-default benchmark harness
``test/control/test_rqpcontrollers.py:101-156`` (``_plot_convergence_rate``):
sample random desired accelerations, run both distributed solvers with tolerance
0 and a fixed iteration budget from a cold start, and plot consensus-residual
vs iteration curves with min/max bands. Here the samples are one ``vmap`` batch
instead of a sequential Python loop.

Usage: python examples/convergence_rates.py [--samples 100] [--iters 25]

``--effort fixed|adaptive|ab`` switches to the adaptive-solver-effort
A/B: instead of the tolerance-0 residual curves, run the batch at the
paper's real stop tolerance (1e-2 N) with the controllers' ``effort``
knob pinned, and print the consensus-iteration histograms (plus the
adaptive arm's inner-effort histogram) — the straggler-spread evidence
the chip-round flip criterion at ``socp.resolve_effort`` reads, and the
exact corpus the ROADMAP's amortized-warm-start follow-up would train
on. ``ab`` runs both arms and prints them side by side.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def _effort_ab(args) -> None:
    """The --effort mode: per-sample iteration-count histograms at the
    real stop tolerance, fixed vs adaptive."""
    from tpu_aerial_transport.control import cadmm, centralized, dd
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.obs import telemetry as telemetry_mod

    params, col, state0 = setup.rqp_setup(args.n)
    f_eq = centralized.equilibrium_forces(params)
    keys = jax.random.split(jax.random.PRNGKey(0), args.samples)
    accs = jax.vmap(lambda k: 0.5 * jax.random.normal(k, (3,)))(keys)
    edges = list(telemetry_mod.ITER_BUCKETS)
    labels = [f"<={e}" for e in edges] + [f">{edges[-1]}"]

    def hist_line(values):
        # The shared right-closed bucketing (v <= edge), so these lines
        # read on the same axis as the telemetry accumulators and the
        # bench cells' iters_hist fields.
        h = telemetry_mod.iter_histogram(values)
        parts = [f"{lab}: {int(c)}" for lab, c in zip(labels, h) if c > 0]
        return ", ".join(parts) or "(empty)"

    modes = ("fixed", "adaptive") if args.effort == "ab" else (args.effort,)
    summary = {}
    for effort in modes:
        acfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=args.iters, inner_iters=80, effort=effort,
        )
        dcfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=args.iters, inner_iters=80, effort=effort,
        )

        def cadmm_run(acc):
            astate = cadmm.init_cadmm_state(params, acfg)
            _, _, stats = cadmm.control(
                params, acfg, f_eq, astate, state0, (acc, jnp.zeros(3))
            )
            return stats.iters, stats.solve_res, stats.inner_iters

        def dd_run(acc):
            dstate = dd.init_dd_state(params, dcfg)
            _, _, stats = dd.control(
                params, dcfg, f_eq, dstate, state0, (acc, jnp.zeros(3))
            )
            return stats.iters, stats.solve_res, stats.inner_iters

        print(f"\n== effort={effort} ({args.samples} samples, "
              f"max_iter={args.iters}, res_tol 1e-2 N) ==")
        for label, run in (("C-ADMM", cadmm_run), ("DD", dd_run)):
            iters, res, inner = jax.jit(jax.vmap(run))(accs)
            iters = np.asarray(iters)
            res = np.asarray(res)
            row = {
                "iters_mean": float(iters.mean()),
                "iters_p99": float(np.percentile(iters, 99)),
                "res_max": float(res.max()),
            }
            print(f"{label}: consensus iters mean {row['iters_mean']:.1f} "
                  f"p99 {row['iters_p99']:.0f}, worst residual "
                  f"{row['res_max']:.2e} N")
            print(f"  consensus-iteration histogram: {hist_line(iters)}")
            if np.asarray(inner).size:
                # Per-solve effort (the telemetry accumulators' axis).
                per = np.asarray(inner) / np.maximum(iters, 1) / args.n
                row["inner_per_solve_mean"] = float(per.mean())
                print(f"  inner iters/solve: mean {per.mean():.1f} "
                      f"p99 {np.percentile(per, 99):.0f}")
                print(f"  inner-effort histogram: {hist_line(per)}")
            summary[f"{label}_{effort}"] = row
    if args.json:
        import json

        with open(args.json, "w") as fh:
            json.dump({"n": args.n, "samples": args.samples,
                       "iters": args.iters, "mode": "effort_ab",
                       **summary}, fh, indent=1)
        print(f"\neffort summary saved to {args.json}")


def main() -> None:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over site hooks.
    p = argparse.ArgumentParser()
    p.add_argument("--samples", type=int, default=100)
    p.add_argument("--iters", type=int, default=25)
    p.add_argument("-n", type=int, default=3)
    p.add_argument("--out", default="convergence_rates.png")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write per-iteration median/min/max residuals "
                        "for both solvers as JSON")
    p.add_argument("--effort", choices=["fixed", "adaptive", "ab"],
                   default=None,
                   help="adaptive-solver-effort A/B: run at the real stop "
                        "tolerance and print iteration histograms instead "
                        "of the tolerance-0 residual curves")
    args = p.parse_args()

    if args.effort:
        _effort_ab(args)
        return

    from tpu_aerial_transport.control import cadmm, centralized, dd
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.viz import plots

    params, col, state0 = setup.rqp_setup(args.n)
    f_eq = centralized.equilibrium_forces(params)
    # Tolerance 0 + fixed budget (the reference sets tol=0, max_iter=25).
    acfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=args.iters, inner_iters=80, res_tol=0.0,
    )
    dcfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=args.iters, inner_iters=80, prim_inf_tol=0.0,
    )

    keys = jax.random.split(jax.random.PRNGKey(0), args.samples)
    accs = jax.vmap(lambda k: 0.5 * jax.random.normal(k, (3,)))(keys)

    def cadmm_run(acc):
        astate = cadmm.init_cadmm_state(params, acfg)
        _, _, stats = cadmm.control(
            params, acfg, f_eq, astate, state0, (acc, jnp.zeros(3))
        )
        return stats.err_seq

    def dd_run(acc):
        dstate = dd.init_dd_state(params, dcfg)
        _, _, stats = dd.control(
            params, dcfg, f_eq, dstate, state0, (acc, jnp.zeros(3))
        )
        return stats.err_seq

    print(f"running {args.samples} samples x {args.iters} iterations ...")
    cadmm_errs = np.asarray(jax.jit(jax.vmap(cadmm_run))(accs))
    dd_errs = np.asarray(jax.jit(jax.vmap(dd_run))(accs))

    summary = {}
    for label, errs in (("C-ADMM", cadmm_errs), ("DD", dd_errs)):
        final = errs[:, min(args.iters, errs.shape[1]) - 1]
        final = final[~np.isnan(final)]
        print(f"{label}: median residual after {args.iters} iters: "
              f"{np.median(final):.2e} N")
        with np.errstate(all="ignore"):
            summary[label] = {
                "median": np.nanmedian(errs, axis=0).tolist(),
                "min": np.nanmin(errs, axis=0).tolist(),
                "max": np.nanmax(errs, axis=0).tolist(),
            }

    if args.json:
        import json

        with open(args.json, "w") as fh:
            json.dump({
                "n": args.n, "samples": args.samples, "iters": args.iters,
                "unit": "N (inf-norm consensus / primal-infeasibility "
                        "residual per iteration, cold start, tol 0)",
                **summary,
            }, fh, indent=1)
        print(f"residual curves saved to {args.json}")

    plots.plot_convergence_rates(
        {"C-ADMM": cadmm_errs, "DD": dd_errs}, args.out
    )
    print(f"figure saved to {args.out}")


if __name__ == "__main__":
    main()
