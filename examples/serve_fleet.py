#!/usr/bin/env python
"""Serving-fleet demo: a seeded mixed-tenant request stream through N
replica ``ScenarioServer`` processes behind the consistent-hash
admission front (``tpu_aerial_transport/serving/fleet.py``), with an
optional chaos storm layered on top.

This is a thin, opinionated wrapper over ``tools/fleet_local.py`` — the
harness owns the process discipline (own-session workers, group kills,
parent-pid watchdogs, fsync'd jsonl channels); the demo picks a
believable multi-tenant workload and narrates the outcome:

- three tenants with different admission contracts — ``pro`` (high
  weight, priority), ``free`` (rate-limited token bucket), ``batch``
  (best-effort) — so the weighted-fair dequeue and structured
  ``tenant_rate_limited`` rejections are visible in one run;
- ``--chaos`` arms a seeded :class:`FleetFaultPlan` (SIGKILL a replica
  mid-batch, wedge another) and the summary shows the supervisor's
  ``up -> down -> restarting -> up`` transitions, the failover count,
  and — with ``--trace`` — the explicit ``retry`` segment on each
  failed-over request's ORIGINAL trace_id in the stitched Perfetto
  trace;
- every completed request reports a result digest, so a chaos run can
  be diffed bit-for-bit against a fault-free run of the same seed.

Usage:
  python examples/serve_fleet.py --replicas 2 --requests 12
  python examples/serve_fleet.py --replicas 2 --chaos --trace
  python examples/serve_fleet.py --replicas 3 --chaos --seed 7 \\
      --trace --out-dir artifacts/fleet-demo

On a 1-core host multi-replica runs skip with a written reason (the
harness prints the skip JSON); pass ``--force-multi`` to override.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import fleet_local  # noqa: E402  (tools/fleet_local.py)

DEMO_TENANTS = "pro:weight=4,priority=1;free:rate=2,burst=3;batch:weight=1"


def main(argv=None) -> int:
    parser = fleet_local.build_parser()
    parser.description = __doc__
    parser.set_defaults(
        requests=12,
        tenants=DEMO_TENANTS,
        out_dir="artifacts/fleet-demo",
        poisson_rate=4.0,
        # Spread the seeded storm wide enough to land after replica
        # boot (faults sent while a worker is still replaying its inbox
        # are live-only and dropped — a storm over 0..4s would miss).
        chaos_span=12.0,
    )
    # The demo accepts bare ``--chaos`` (arm a seeded storm) and bare
    # ``--trace`` (auto-pathed Perfetto output); the harness parser
    # takes explicit values for both, so backfill placeholders before
    # parsing. Explicit values (``--chaos sigkill@2:r0``) pass through.
    argv = list(sys.argv[1:] if argv is None else argv)
    for flag, placeholder in (("--chaos", "seeded"), ("--trace", "auto")):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 == len(argv) or argv[i + 1].startswith("-"):
                argv.insert(i + 1, placeholder)
    args = parser.parse_args(argv)
    if args.chaos == "seeded":
        args.chaos = f"seeded:{args.seed}"
    if args.trace == "auto":
        args.trace = os.path.join(args.out_dir, "fleet.trace.json")

    if (os.cpu_count() or 1) < 2 and args.replicas > 1 \
            and not args.force_multi:
        print(json.dumps({
            "skipped": f"1-core host (os.cpu_count()={os.cpu_count()}): "
                       f"cannot run {args.replicas} fleet replicas "
                       "reliably (--force-multi overrides)"
        }))
        return 0

    summary, rc = fleet_local.run_fleet(args)

    # Live-SLO pass (obs/live.py): replay every replica journal the run
    # left in --out-dir through a MetricsHub + burn-rate engine; the
    # final hub snapshot rides the summary and an alert still firing at
    # end-of-run turns a passing run into exit 6 (rc != 0 keeps its own
    # code — don't mask a harness failure with the SLO verdict).
    from tpu_aerial_transport.obs import live as live_mod

    hub = live_mod.MetricsHub()
    # The demo's ``free`` tenant is rate-limited BY CONTRACT — its
    # token-bucket rejections are the admission design working, not an
    # SLO violation — so the rejection SLO is scoped to ``pro`` (the
    # tenant that bought priority) while latency/miss stay fleet-wide.
    engine = live_mod.SLOEngine((
        live_mod.SLOSpec(name="step_p99", metric="step_latency",
                         objective=0.99, threshold_s=30.0),
        live_mod.SLOSpec(name="miss_rate", metric="deadline_miss",
                         objective=0.99),
        live_mod.SLOSpec(name="rejection", metric="rejection",
                         objective=0.95, tenant="pro"),
    ))
    tailer = live_mod.FleetTailer([args.out_dir])
    for replica, event in tailer.poll():
        engine.ingest(replica, event)
        etype = event.get("event")
        if etype == "serving_event":
            hub.ingest_serving(event)
        elif etype == "session_event":
            hub.ingest_session(event)
        elif etype == "backend_event":
            hub.ingest_backend(event)
        elif etype == "aot_serve":
            hub.ingest_aot(event)
    engine.evaluate()
    firing = sorted(f"{n}/{t}" for n, t in engine.firing)
    summary["slo"] = {"firing": firing, "alerts": len(engine.alerts)}
    summary["hub"] = hub.snapshot()

    # Narrate the interesting bits above the raw summary.
    notes = []
    tenants = summary.get("tenants", {})
    for name in sorted(tenants):
        t = tenants[name]
        notes.append(
            f"tenant {name}: {t['completed']}/{t['submitted']} completed"
            + (f", {t['rejected']} rejected" if t["rejected"] else "")
        )
    if summary.get("failovers"):
        notes.append(
            f"failovers: {summary['failovers']} request(s) re-dispatched "
            "off dead replicas (same trace_id; retry segment in trace)"
        )
    if summary.get("trace"):
        notes.append(f"perfetto trace: {summary['trace']['path']}")
    if firing:
        notes.append(f"SLO ALERTS FIRING at end of run: "
                     f"{', '.join(firing)}")
    summary["notes"] = notes
    print(json.dumps(summary, indent=1))
    if rc == 0 and firing:
        print(f"serve_fleet: unresolved firing alerts: {firing}",
              file=sys.stderr)
        return 6
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
