#!/usr/bin/env python
"""Serving-fleet demo: a seeded mixed-tenant request stream through N
replica ``ScenarioServer`` processes behind the consistent-hash
admission front (``tpu_aerial_transport/serving/fleet.py``), with an
optional chaos storm layered on top.

This is a thin, opinionated wrapper over ``tools/fleet_local.py`` — the
harness owns the process discipline (own-session workers, group kills,
parent-pid watchdogs, fsync'd jsonl channels); the demo picks a
believable multi-tenant workload and narrates the outcome:

- three tenants with different admission contracts — ``pro`` (high
  weight, priority), ``free`` (rate-limited token bucket), ``batch``
  (best-effort) — so the weighted-fair dequeue and structured
  ``tenant_rate_limited`` rejections are visible in one run;
- ``--chaos`` arms a seeded :class:`FleetFaultPlan` (SIGKILL a replica
  mid-batch, wedge another) and the summary shows the supervisor's
  ``up -> down -> restarting -> up`` transitions, the failover count,
  and — with ``--trace`` — the explicit ``retry`` segment on each
  failed-over request's ORIGINAL trace_id in the stitched Perfetto
  trace;
- every completed request reports a result digest, so a chaos run can
  be diffed bit-for-bit against a fault-free run of the same seed.

Usage:
  python examples/serve_fleet.py --replicas 2 --requests 12
  python examples/serve_fleet.py --replicas 2 --chaos --trace
  python examples/serve_fleet.py --replicas 3 --chaos --seed 7 \\
      --trace --out-dir artifacts/fleet-demo

On a 1-core host multi-replica runs skip with a written reason (the
harness prints the skip JSON); pass ``--force-multi`` to override.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import fleet_local  # noqa: E402  (tools/fleet_local.py)

DEMO_TENANTS = "pro:weight=4,priority=1;free:rate=2,burst=3;batch:weight=1"


def main(argv=None) -> int:
    parser = fleet_local.build_parser()
    parser.description = __doc__
    parser.set_defaults(
        requests=12,
        tenants=DEMO_TENANTS,
        out_dir="artifacts/fleet-demo",
        poisson_rate=4.0,
        # Spread the seeded storm wide enough to land after replica
        # boot (faults sent while a worker is still replaying its inbox
        # are live-only and dropped — a storm over 0..4s would miss).
        chaos_span=12.0,
    )
    # The demo accepts bare ``--chaos`` (arm a seeded storm) and bare
    # ``--trace`` (auto-pathed Perfetto output); the harness parser
    # takes explicit values for both, so backfill placeholders before
    # parsing. Explicit values (``--chaos sigkill@2:r0``) pass through.
    argv = list(sys.argv[1:] if argv is None else argv)
    for flag, placeholder in (("--chaos", "seeded"), ("--trace", "auto")):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 == len(argv) or argv[i + 1].startswith("-"):
                argv.insert(i + 1, placeholder)
    args = parser.parse_args(argv)
    if args.chaos == "seeded":
        args.chaos = f"seeded:{args.seed}"
    if args.trace == "auto":
        args.trace = os.path.join(args.out_dir, "fleet.trace.json")

    if (os.cpu_count() or 1) < 2 and args.replicas > 1 \
            and not args.force_multi:
        print(json.dumps({
            "skipped": f"1-core host (os.cpu_count()={os.cpu_count()}): "
                       f"cannot run {args.replicas} fleet replicas "
                       "reliably (--force-multi overrides)"
        }))
        return 0

    summary, rc = fleet_local.run_fleet(args)

    # Narrate the interesting bits above the raw summary.
    notes = []
    tenants = summary.get("tenants", {})
    for name in sorted(tenants):
        t = tenants[name]
        notes.append(
            f"tenant {name}: {t['completed']}/{t['submitted']} completed"
            + (f", {t['rejected']} rejected" if t["rejected"] else "")
        )
    if summary.get("failovers"):
        notes.append(
            f"failovers: {summary['failovers']} request(s) re-dispatched "
            "off dead replicas (same trace_id; retry segment in trace)"
        )
    if summary.get("trace"):
        notes.append(f"perfetto trace: {summary['trace']['path']}")
    summary["notes"] = notes
    print(json.dumps(summary, indent=1))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
