"""Main simulation entry point: RQP team flying through the forest under
centralized / C-ADMM / dual-decomposition MPC.

TPU-native counterpart of reference ``example/rqp_example.py:main()``: same
workload shape (n agents, dt = 1e-3 s, high-level control at 100 Hz, forest env,
terrain-following reference trajectory), but the whole rollout is one jitted
two-rate ``lax.scan`` and the controller is selected by CLI flag instead of
editing the source (the reference's config story, SURVEY.md §5.6).

Usage:
  python examples/rqp_forest.py --controller centralized -T 10
  python examples/rqp_forest.py --controller cadmm -n 8 -T 5 --plots

Preemption-safe runs (harness.checkpoint + resilience.recovery): split the
rollout into checkpointed chunks, survive SIGTERM/SIGINT at any boundary,
and resume bit-exactly from the journal:

  python examples/rqp_forest.py --controller cadmm -T 10 \
      --chunks 10 --ckpt-dir /tmp/run1
  # ... kill it mid-run, then:
  python examples/rqp_forest.py --resume /tmp/run1

Flight-recorder telemetry (obs/): accumulate run-health metrics on-device
and export a schema-versioned metrics jsonl, rendered by run_health:

  python examples/rqp_forest.py --controller cadmm -T 2 --telemetry \
      --chunks 4 --ckpt-dir /tmp/run2
  python tools/run_health.py /tmp/run2/run.metrics.jsonl
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over site hooks.
    p = argparse.ArgumentParser()
    p.add_argument("--controller", default="centralized",
                   choices=["centralized", "cadmm", "dd"])
    p.add_argument("-n", type=int, default=3, help="number of quadrotors")
    p.add_argument("-T", type=float, default=10.0, help="sim horizon [s]")
    p.add_argument("--dt", type=float, default=1e-3)
    p.add_argument("--hl-rel-freq", type=int, default=10)
    p.add_argument("--seed", type=int, default=0, help="forest seed")
    p.add_argument("--out", default=None, help="npz log path")
    p.add_argument("--plots", action="store_true", help="save figures")
    p.add_argument("--time-chunk", type=int, default=10, metavar="C",
                   help="MPC steps per timed scan chunk for the wall-clock "
                        "statistics (0 disables the timing pass)")
    p.add_argument("--chunks", type=int, default=0, metavar="C",
                   help="run as C checkpointed chunks (one compiled chunk, "
                        "snapshot + journal at every boundary; needs "
                        "--ckpt-dir; SIGTERM/SIGINT stop gracefully)")
    p.add_argument("--ckpt-dir", default=None, metavar="DIR",
                   help="run directory for --chunks (journal.jsonl + "
                        "carry/logs snapshots)")
    p.add_argument("--resume", default=None, metavar="DIR",
                   help="resume a --chunks run from DIR's journal; the "
                        "run's settings (controller/n/T/seed/...) are "
                        "restored from the journal and the matching CLI "
                        "flags are ignored")
    p.add_argument("--telemetry", action="store_true",
                   help="thread the in-jit run-health accumulator "
                        "(obs.telemetry) through the rollout carry")
    p.add_argument("--metrics", default=None, metavar="PATH",
                   help="metrics jsonl path (obs.export; default with "
                        "--chunks: <ckpt-dir>/run.metrics.jsonl). Render "
                        "with tools/run_health.py")
    args = p.parse_args()

    from tpu_aerial_transport.control import cadmm, centralized, dd, lowlevel
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import rollout as ro
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.utils.stats import compute_aggregate_statistics

    if args.resume:
        from tpu_aerial_transport.resilience import recovery

        plan = recovery.read_plan(args.resume)
        meta = plan.meta
        print(f"resuming from {args.resume}: {meta} "
              f"({plan.n_chunks} chunks of {plan.chunk_len} MPC steps)")
        # Deterministic regen: everything the run depends on is journaled.
        args.controller = meta["controller"]
        args.n = meta["n"]
        args.T = meta["T"]
        args.dt = meta["dt"]
        args.hl_rel_freq = meta["hl_rel_freq"]
        args.seed = plan.seed
        args.chunks = plan.n_chunks
        args.ckpt_dir = args.resume
        # The telemetry accumulator is part of the chunk carry: the resumed
        # chunk program must match the journaled one structurally.
        args.telemetry = bool(meta.get("telemetry", False))

    params, col, state0 = setup.rqp_setup(args.n)
    forest = forest_mod.make_forest(seed=args.seed)
    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    acc_des_fn = ro.make_forest_acc_des(forest)
    state0 = state0.replace(xl=jnp.array([0.0, 0.0, 1.5], jnp.float32))

    if args.controller == "centralized":
        cfg = centralized.make_config(
            params, col.collision_radius, col.max_deceleration
        )
        cs0 = centralized.init_ctrl_state(params, cfg)

        def hl(cs, s, acc):
            env_cbf = forest_mod.collision_cbf_rows(
                forest, s.xl, s.vl, col.collision_radius, col.max_deceleration,
                cfg.vision_radius, cfg.dist_eps, cfg.alpha_env_cbf,
                cfg.n_env_cbfs,
            )
            return centralized.control(params, cfg, f_eq, cs, s, acc, env_cbf)

        dist_eps = cfg.dist_eps
    elif args.controller == "cadmm":
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration
        )
        cs0 = cadmm.init_cadmm_state(params, cfg)
        plan = cadmm.make_plan(params, cfg)
        hl = lambda cs, s, acc: cadmm.control(
            params, cfg, f_eq, cs, s, acc, forest, plan=plan
        )
        dist_eps = cfg.dist_eps
    else:
        cfg = dd.make_config(params, col.collision_radius, col.max_deceleration)
        cs0 = dd.init_dd_state(params, cfg)
        dd_plan = dd.make_dd_plan(params, cfg)
        hl = lambda cs, s, acc: dd.control(
            params, cfg, f_eq, cs, s, acc, forest, plan=dd_plan
        )
        dist_eps = cfg.base.dist_eps

    n_hl_steps = int(args.T / (args.dt * args.hl_rel_freq))
    tcfg = None
    if args.telemetry:
        from tpu_aerial_transport.obs import telemetry as telemetry_mod

        tcfg = telemetry_mod.TelemetryConfig()
    # chunks >= 1 (not >= 2): asking for ONE checkpointed chunk is a valid
    # request (snapshot at the end, resumable journal) — silently running
    # the snapshot-less path would strand a later --resume.
    checkpointed = args.chunks >= 1 or args.resume
    if checkpointed:
        from tpu_aerial_transport.harness import checkpoint
        from tpu_aerial_transport.resilience import recovery

        if not args.ckpt_dir:
            raise SystemExit("--chunks needs --ckpt-dir")
        if n_hl_steps % args.chunks:
            raise SystemExit(
                f"T gives {n_hl_steps} MPC steps, not divisible by "
                f"--chunks {args.chunks}"
            )
        config_hash = checkpoint.config_fingerprint(
            controller=args.controller, n=args.n, seed=args.seed,
            dt=args.dt, hl_rel_freq=args.hl_rel_freq, cfg=cfg,
        )
        metrics_path = args.metrics or os.path.join(
            args.ckpt_dir, "run.metrics.jsonl"
        )
        runner = ro.make_chunked_rollout(
            hl, ll.control, params, n_hl_steps=n_hl_steps,
            n_chunks=args.chunks, hl_rel_freq=args.hl_rel_freq, dt=args.dt,
            acc_des_fn=acc_des_fn, telemetry=tcfg,
        )
        # Decouple constant-deduped zero leaves before the chunk donates
        # the carry (see harness.rollout.jit_rollout's caveat).
        carry0 = runner.init_carry(*jax.tree.map(jnp.copy, (state0, cs0)))
        print(f"compiling + running {args.controller}, n={args.n}, "
              f"{n_hl_steps} MPC steps in {args.chunks} checkpointed "
              f"chunks -> {args.ckpt_dir} ...")
        t0 = time.perf_counter()
        with recovery.GracefulInterrupt() as interrupt:
            if args.resume:
                res = recovery.resume_run(
                    args.resume, runner.chunk_jit, carry0,
                    config_hash=config_hash, interrupt=interrupt,
                    metrics=metrics_path,
                )
                print(f"resumed from chunk {res.resumed_from_chunk}")
            else:
                # NOTE the name: the cadmm/dd Schur/QN `plan` above is
                # captured late-bound by the `hl` lambda — rebinding `plan`
                # here would hand the controller a RunPlan mid-rollout.
                run_plan = recovery.RunPlan(
                    run_dir=args.ckpt_dir, n_hl_steps=n_hl_steps,
                    n_chunks=args.chunks, seed=args.seed,
                    config_hash=config_hash,
                    meta={"controller": args.controller, "n": args.n,
                          "T": args.T, "dt": args.dt,
                          "hl_rel_freq": args.hl_rel_freq,
                          "telemetry": bool(args.telemetry)},
                )
                res = recovery.run_chunks(
                    run_plan, runner.chunk_jit, carry0, interrupt=interrupt,
                    metrics=metrics_path,
                )
        dt_wall = time.perf_counter() - t0
        if res.status == "preempted":
            raise SystemExit(
                f"preempted at chunk {res.chunks_done}/{args.chunks} after "
                f"{dt_wall:.1f} s — state is snapshotted; continue with: "
                f"python examples/rqp_forest.py --resume {args.ckpt_dir}"
            )
        final, logs = res.carry[0], res.logs
        print(f"done in {dt_wall:.1f} s ({n_hl_steps / dt_wall:.1f} MPC "
              f"steps/s incl. compile)")
    else:
        run = jax.jit(
            lambda s0, c0: ro.rollout(
                hl, ll.control, params, s0, c0, n_hl_steps=n_hl_steps,
                hl_rel_freq=args.hl_rel_freq, dt=args.dt,
                acc_des_fn=acc_des_fn, telemetry=tcfg,
            )
        )
        print(f"compiling + running {args.controller}, n={args.n}, "
              f"{n_hl_steps} MPC steps ...")
        t0 = time.perf_counter()
        if tcfg is not None:
            final, _, logs, tel = run(state0, cs0)
        else:
            final, _, logs = run(state0, cs0)
            tel = None
        jax.block_until_ready(final.xl)
        dt_wall = time.perf_counter() - t0
        print(f"done in {dt_wall:.1f} s ({n_hl_steps / dt_wall:.1f} MPC "
              f"steps/s incl. compile)")
        if args.metrics or tel is not None:
            # On-demand export from rollout results (obs.export).
            from tpu_aerial_transport.obs import export as export_mod

            path = args.metrics or "artifacts/rollout.metrics.jsonl"
            export_mod.rollout_metrics(
                path, logs, tel, tcfg,
                meta={"controller": args.controller, "n": args.n,
                      "T": args.T},
            )
            print(f"metrics written to {path} "
                  f"(render: python tools/run_health.py {path})")

    # Aggregate stats (reference _print_stats, rqp_example.py:62-80).
    iters = np.asarray(logs.iters)
    if (iters >= 0).any():
        mn, mx, avg, std = (float(x) for x in
                            compute_aggregate_statistics(iters[iters >= 0]))
        print(f"Solver iterations: min: {mn:5.2f}, max: {mx:5.2f}, "
              f"avg: {avg:5.2f}, std: {std:5.2f}")

    # Per-MPC-step wall-clock statistics (the reference prints Clarabel's
    # per-solve times the same way, rqp_example.py:62-80). Host timing of a
    # single fused step would mostly measure ~100 ms dispatch latency through
    # the device tunnel, so the rollout re-runs as jitted scan CHUNKS of
    # --time-chunk MPC steps, each timed on the host; every sample below is a
    # chunk's wall time divided by its step count.
    if args.time_chunk > 0:
        chunk = min(args.time_chunk, n_hl_steps)
        run_chunk = jax.jit(
            lambda s0, c0: ro.rollout(
                hl, ll.control, params, s0, c0, n_hl_steps=chunk,
                hl_rel_freq=args.hl_rel_freq, dt=args.dt,
                acc_des_fn=acc_des_fn,
            )
        )
        s, c, _ = run_chunk(state0, cs0)  # compile at the chunk length.
        jax.block_until_ready(s.xl)
        s, c = state0, cs0
        samples = []
        for _ in range(max(2, n_hl_steps // chunk)):
            t0 = time.perf_counter()
            s, c, _ = run_chunk(s, c)
            jax.block_until_ready(s.xl)
            samples.append((time.perf_counter() - t0) / chunk)
        mn, mx, avg, std = (
            1e3 * float(x)
            for x in compute_aggregate_statistics(np.asarray(samples))
        )
        print(f"Solve time per MPC step [ms] (chunks of {chunk}): "
              f"min: {mn:6.3f}, max: {mx:6.3f}, avg: {avg:6.3f}, "
              f"std: {std:6.3f}")
    print(f"final payload position: {np.asarray(final.xl)}")
    print(f"min env distance over run: {float(np.min(np.asarray(logs.min_env_dist))):.3f} m "
          f"(eps = {dist_eps})")
    print(f"collisions: {int(np.sum(np.asarray(logs.collision)))}")

    log_dict = ro.logs_to_dict(logs, args.n, args.dt, args.hl_rel_freq, forest)
    if args.out:
        parent = os.path.dirname(os.path.abspath(args.out))
        os.makedirs(parent, exist_ok=True)
        np.savez(args.out, **{
            k: v for k, v in log_dict.items() if not isinstance(v, dict)
        }, **{f"state_{k}": v for k, v in log_dict["state_seq"].items()})
        print(f"logs saved to {args.out}")
    if args.plots:
        from tpu_aerial_transport.viz import plots

        plots.save_figures(log_dict, "", args.controller,
                           params=params, collision=col, dist_eps=dist_eps)
        print("figures saved (xy + min-dist at 600 dpi)")


if __name__ == "__main__":
    main()
