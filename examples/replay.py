"""Replay a saved rollout log: frames, ghost snapshot, and paper figures.

TPU-native counterpart of reference ``example/rqp_plots.py:main()`` (:496-527):
loads the run artifact (npz written by ``examples/rqp_forest.py --out``),
reconstructs the forest from the logged tree positions (reference :503-505 —
the procedural env is reproducible from the log), and renders:

- PNG replay frames with the smoothed follow camera (``viz.scene.render_frames``;
  use ``--meshcat`` for the live three.js viewer with camera pacing),
- a multi-ghost snapshot scene (reference ``_snapshot``),
- the paper figures: 600-dpi xy trajectory with key-frame overlays and the
  min-distance log plot.

Usage:
  python examples/rqp_forest.py --controller cadmm -T 10 --out run.npz
  python examples/replay.py run.npz --controller cadmm --outdir replay_out
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def load_log(path: str) -> dict:
    """Inverse of the flattened npz layout written by rqp_forest.py."""
    raw = np.load(path, allow_pickle=False)
    logs = {k: raw[k] for k in raw.files if not k.startswith("state_")}
    logs["state_seq"] = {
        k[len("state_"):]: raw[k] for k in raw.files if k.startswith("state_")
    }
    for k in ("n", "dt", "T", "hl_rel_freq", "log_freq", "num_trees"):
        if k in logs:
            logs[k] = logs[k].item()
    return logs


def main() -> None:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over site hooks.
    p = argparse.ArgumentParser()
    p.add_argument("log", help="npz log from rqp_forest.py --out")
    p.add_argument("--controller", default="cadmm",
                   choices=["centralized", "cadmm", "dd"])
    p.add_argument("--outdir", default="replay_out")
    p.add_argument("--stride", type=int, default=25, help="frame stride")
    p.add_argument("--force-arrows", action="store_true",
                   help="overlay per-agent commanded-force arrows "
                        "(reference _DRAW_FORCE_ARROWS; needs f_des_seq "
                        "in the log)")
    p.add_argument("--meshcat", action="store_true",
                   help="live meshcat replay instead of PNG frames")
    args = p.parse_args()

    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.viz import plots, scene

    logs = load_log(args.log)
    n = int(logs["n"])
    params, col, _ = setup.rqp_setup(n)
    forest = None
    if "tree_pos" in logs:
        forest = forest_mod.forest_from_tree_pos(
            logs["tree_pos"], logs.get("num_trees", len(logs["tree_pos"]))
        )

    os.makedirs(args.outdir, exist_ok=True)

    if args.meshcat:
        backend = scene.MeshcatBackend().open()
        backend.replay(logs, params, payload_vertices=col.payload_vertices,
                       forest=forest, force_arrows=args.force_arrows)
    else:
        frames = scene.render_frames(
            logs, params, col.payload_vertices,
            os.path.join(args.outdir, "frames"), forest=forest,
            stride=args.stride, force_arrows=args.force_arrows,
        )
        print(f"{len(frames)} frames -> {args.outdir}/frames")

    T = logs["state_seq"]["xl"].shape[0]
    scene.render_ghost_snapshot(
        logs, params, col.payload_vertices,
        os.path.join(args.outdir, "ghosts.png"),
        times=[int(f * (T - 1)) for f in (0.1, 0.4, 0.7, 0.95)],
        forest=forest,
    )
    plots.save_figures(logs, args.outdir, args.controller,
                       params=params, collision=col)
    print(f"figures -> {args.outdir}")


if __name__ == "__main__":
    main()
