"""City-scale forest demo: a >=10^4-obstacle world through the bucketed
environment-query tier and the flight-recorder telemetry path.

The paper's world is the 200-tree mountain forest; the dense O(max_trees)
capsule sweep caps world size there. This demo builds a jittered-grid city
world (default 16384 trees, ~80x the reference — a world the dense sweep
cannot afford), attaches the spatial-hash grid artifact
(``envs.spatial.with_grid``), and runs a C-ADMM rollout whose
``env_query="auto"`` config resolves to the bucketed tier at trace time
(the world's slot count exceeds ``spatial.DENSE_AUTO_MAX_TREES``), with
the in-jit run-health telemetry accumulator on the carry:

  python examples/city_forest.py --trees 16384 -T 0.5
  python examples/city_forest.py --trees 65536 -n 4 --metrics \
      /tmp/city.metrics.jsonl
  python tools/run_health.py /tmp/city.metrics.jsonl

Printed at the end: the grid's occupancy telemetry (cells, slab width K,
max/mean occupancy — the structured record whose build-time counterpart
is the GridOverflowError refusal), the rollout's safety margins from the
telemetry accumulator, and the wall rate.
"""

from __future__ import annotations

import argparse
import math
import time

import jax
import jax.numpy as jnp


def main() -> None:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    p = argparse.ArgumentParser()
    p.add_argument("--trees", type=int, default=16384,
                   help="tree count (a square number: jittered-grid world)")
    p.add_argument("--density", type=float, default=0.085,
                   help="trees/m^2 (must respect the 3.2 m min spacing)")
    p.add_argument("-n", type=int, default=4, help="number of quadrotors")
    p.add_argument("-T", type=float, default=0.5, help="sim horizon [s]")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--env-query", default="auto",
                   choices=["auto", "dense", "bucketed"],
                   help="query impl (auto resolves to bucketed at this "
                        "world size; dense will refuse the memory bill)")
    p.add_argument("--metrics", default=None, metavar="PATH",
                   help="write a rollout_summary metrics event "
                        "(obs.export; render with tools/run_health.py)")
    args = p.parse_args()

    from tpu_aerial_transport.control import cadmm, centralized, lowlevel
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.envs import spatial as spatial_mod
    from tpu_aerial_transport.harness import rollout as ro
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.obs import telemetry as telemetry_mod

    n_side = math.isqrt(args.trees)
    if n_side * n_side != args.trees:
        raise SystemExit(f"--trees {args.trees} must be a square number")
    pitch = 1.0 / math.sqrt(args.density)
    world_size = (n_side + 0.5) * pitch

    params, col, state0 = setup.rqp_setup(args.n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        env_query=args.env_query,
    )

    t0 = time.perf_counter()
    forest = forest_mod.make_forest(
        seed=args.seed, max_trees=args.trees, world_size=world_size,
        density=args.density,
    )
    forest = spatial_mod.with_grid(
        forest, cfg.vision_radius + forest.bark_radius
    )
    stats = spatial_mod.grid_stats(forest.grid)
    print(f"world: {int(forest.num_trees)} trees over "
          f"{world_size:.0f} x {world_size:.0f} m "
          f"(built in {time.perf_counter() - t0:.2f} s)")
    print(f"grid: {stats['n_cells']} cells of {stats['cell_size_m']:.1f} m, "
          f"slab K={stats['k']}, occupancy max {stats['max_occupancy']} / "
          f"mean {stats['mean_occupancy']:.1f} — the query gathers "
          f"{stats['k']} candidates instead of sweeping "
          f"{int(forest.num_trees)} trees")

    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    plan = cadmm.make_plan(params, cfg)
    cs0 = cadmm.init_cadmm_state(params, cfg)
    acc_des_fn = ro.make_forest_acc_des(forest)
    # Spawn just above the canopy (tree tops sit at ~BARK_HEIGHT): unlike
    # the reference 200-tree world, a city-density world has no guaranteed
    # free slot at the origin.
    state0 = state0.replace(
        xl=jnp.array([0.0, 0.0, forest_mod.BARK_HEIGHT + 1.0],
                     jnp.float32),
        vl=jnp.array([0.5, 0.0, 0.0], jnp.float32),
    )

    def hl(cs, s, acc):
        return cadmm.control(
            params, cfg, f_eq, cs, s, acc, forest, plan=plan
        )

    n_hl_steps = max(int(args.T / (1e-3 * 10)), 1)
    tcfg = telemetry_mod.TelemetryConfig()
    run = jax.jit(
        lambda s0, c0: ro.rollout(
            hl, ll.control, params, s0, c0, n_hl_steps=n_hl_steps,
            hl_rel_freq=10, dt=1e-3, acc_des_fn=acc_des_fn, telemetry=tcfg,
        )
    )
    impl = spatial_mod.runtime_env_query(cfg.env_query, forest)
    print(f"compiling + running cadmm n={args.n}, {n_hl_steps} MPC steps, "
          f"env_query={cfg.env_query} -> {impl} ...")
    t0 = time.perf_counter()
    final, _, logs, tel = run(state0, cs0)
    jax.block_until_ready(final.xl)
    wall = time.perf_counter() - t0
    summary = telemetry_mod.summary(tel, tcfg)
    print(f"done in {wall:.1f} s ({n_hl_steps / wall:.1f} MPC steps/s "
          "incl. compile)")
    print(f"telemetry: min env dist {summary['min_env_dist']:.3f} m, "
          f"collision steps {summary['collision_steps']}, "
          f"consensus iters total {summary['iters_sum']}")

    if args.metrics:
        from tpu_aerial_transport.obs import export as export_mod

        export_mod.rollout_metrics(
            args.metrics, logs, tel=tel, cfg=tcfg,
            meta={"example": "city_forest", "n_trees": int(forest.num_trees),
                  "world_size_m": world_size, "env_query": impl,
                  "grid": stats},
        )
        print(f"metrics written to {args.metrics} "
              "(render: python tools/run_health.py <path>)")


if __name__ == "__main__":
    main()
