#!/usr/bin/env python
"""Closed-loop session demo + acceptance driver: an interactive-session
storm through the session tier (``tpu_aerial_transport/serving/
sessions.py``).

Seeded clients each open a leased session and stream per-step state
deltas; every accepted step is served as one chunk-length internal
request and resolves with an honest rung. The storm doubles as the PR's
end-to-end proofs:

- ``--silent-after N``: client c0 stops heartbeating/stepping after
  step N — its lease TTL expires and the sweep EVICTS it (the lane
  returns to the filler pool at the chunk boundary).
- ``--zombie``: the evicted client retries its OLD lease — heartbeat
  and step both get the structured ``lease_fenced`` rejection (never a
  lane write), then it re-``open``s under a fresh lease and serves
  again from a reset watermark.
- ``--offline-check``: replays every served step's post-delta state as
  a one-shot request and compares result digests — the session's
  served control stream is bitwise equal to the offline rollout of the
  same state stream (lane independence; exit 5 on mismatch).
- ``--run-dir D`` + SIGTERM (or ``--sigterm-after N``) then
  ``--resume``: the session table restores bit-identically from the
  fsync'd journal and the storm completes.
- ``--bundle DIR --require-bundle --expect-zero-compile``: the whole
  storm serves with 0 traces / lowerings / backend compiles (exit 3
  otherwise).

Usage:
  python examples/serve_sessions.py --clients 4 --steps 3
  python examples/serve_sessions.py --clients 4 --lease-s 0.5 \\
      --silent-after 1 --zombie --expect-evicted 1 --expect-fenced 2
  python examples/serve_sessions.py --run-dir /tmp/sess --resume
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
for p in (REPO, HERE):
    if p not in sys.path:
        sys.path.insert(0, p)

from serve_scenarios import _counters, result_digest  # noqa: E402


def client_plan(i: int, steps: int, seed: int):
    """Deterministic per-client state plan: x0/v0 plus one (dx, dv)
    delta per step. Same seed => same plan, so a resumed storm and the
    offline replay reconstruct the identical state stream."""
    import numpy as np

    rng = np.random.default_rng(seed + 1000 * i)
    x0 = (0.3 * i + 0.1, 0.1, 1.0)
    v0 = (0.1, 0.0, 0.0)
    deltas = []
    for _ in range(steps):
        deltas.append((
            tuple(float(v) for v in rng.normal(0, 0.05, 3)),
            tuple(float(v) for v in rng.normal(0, 0.01, 3)),
        ))
    return x0, v0, deltas


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--steps", type=int, default=3,
                    help="control steps per client")
    ap.add_argument("--family", default="cadmm4")
    ap.add_argument("--buckets", default="4,8")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--lease-s", type=float, default=None,
                    help="session lease TTL (default: resolver — "
                         "TAT_SESSION_LEASE_S else 30)")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="per-step deadline SLO (missed steps resolve "
                         "at the hold_last rung, never raise)")
    ap.add_argument("--silent-after", type=int, default=0,
                    help="client c0 goes silent after this step: its "
                         "lease expires and the sweep evicts it")
    ap.add_argument("--zombie", action="store_true",
                    help="the silenced client retries its OLD lease "
                         "(fenced rejections), then re-opens and "
                         "serves one step under the fresh lease")
    ap.add_argument("--offline-check", action="store_true",
                    help="replay served steps as one-shot requests and "
                         "compare digests; exit 5 on any mismatch")
    ap.add_argument("--expect-evicted", type=int, default=-1,
                    help="exit 4 unless exactly N sessions evicted")
    ap.add_argument("--expect-fenced", type=int, default=-1,
                    help="exit 4 unless exactly N fenced rejections")
    ap.add_argument("--bundle", default="")
    ap.add_argument("--require-bundle", action="store_true")
    ap.add_argument("--expect-zero-compile", action="store_true",
                    help="exit 3 unless traces == lowerings == "
                         "backend_compiles == 0")
    ap.add_argument("--run-dir", default="")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--metrics", default="")
    ap.add_argument("--trace", default="",
                    help="write a Chrome/Perfetto trace (session-step "
                         "spans over the per-request spans)")
    ap.add_argument("--results", default="",
                    help="write per-step {request_id: {rung, digest}} "
                         "JSON")
    ap.add_argument("--sigterm-after", type=int, default=0,
                    help="test hook: raise SIGTERM in-process after N "
                         "pump rounds")
    ap.add_argument("--max-rounds", type=int, default=2000,
                    help="hang guard on the pump loop")
    args = ap.parse_args(argv)

    counts = _counters()  # before anything can compile.

    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from tpu_aerial_transport.obs import live as live_mod
    from tpu_aerial_transport.resilience.recovery import GracefulInterrupt
    from tpu_aerial_transport.serving import batcher
    from tpu_aerial_transport.serving import queue as queue_mod
    from tpu_aerial_transport.serving import server as server_mod
    from tpu_aerial_transport.serving import sessions as sessions_mod

    t0 = time.perf_counter()
    buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    chunk_len = batcher.CANONICAL_FAMILIES[args.family].chunk_len
    tracer = None
    if args.trace:
        from tpu_aerial_transport.obs import export as export_mod
        from tpu_aerial_transport.obs import trace as trace_lib

        sink = (export_mod.MetricsWriter(args.metrics)
                if args.metrics else None)
        tracer = trace_lib.Tracer(sink, track="server")
    # Live metrics hub: in-process counters/gauges/latency histograms
    # over the whole storm — the final snapshot rides the summary JSON.
    hub = live_mod.MetricsHub()
    kw = dict(
        families=[args.family], buckets=buckets,
        bundle=args.bundle or None, require_bundle=args.require_bundle,
        run_dir=args.run_dir or None,
        metrics=(tracer.sink if tracer is not None and tracer.sink
                 else args.metrics or None),
        tracer=tracer, hub=hub,
    )

    plans = {f"c{i}": client_plan(i, args.steps, args.seed)
             for i in range(args.clients)}
    rounds = [0]
    state = {"preempted": False}

    def pump_until(host, done):
        """Pump until ``done()`` (bounded); honors SIGTERM/preemption."""
        while not done():
            more = host.pump()
            rounds[0] += 1
            if args.sigterm_after and rounds[0] == args.sigterm_after:
                os.kill(os.getpid(), 15)  # GracefulInterrupt handles it.
            if host.server.preempted:
                state["preempted"] = True
                return False
            if rounds[0] >= args.max_rounds:
                raise SystemExit(
                    f"serve_sessions: stalled after {rounds[0]} rounds")
            if not more and not done():
                return False  # server idle but predicate unmet.
        return True

    digests = {}    # request_id -> digest of SERVED step results.
    results = {}    # request_id -> {status, rung, ...} for --results.
    zombie_log = {}

    def note(step):
        row = {"status": step.status}
        if step.rung:
            row["rung"] = step.rung
        if step.reason:
            row["reason"] = step.reason
        if step.missed:
            row["missed"] = step.missed
        if (step.rung == sessions_mod.RUNG_SERVED
                and step.result is not None):
            d = result_digest(step.result)
            row["digest"] = d
            digests[step.request_id] = d
        results[step.request_id] = row

    with GracefulInterrupt() as interrupt:
        if args.resume:
            server = server_mod.ScenarioServer.resume(
                args.run_dir, **{k: v for k, v in kw.items()
                                 if k != "run_dir"})
            server.interrupt = interrupt
            host = sessions_mod.SessionHost.resume(
                server, lease_s=args.lease_s,
                step_deadline_s=args.deadline_s)
            # Resolve whatever the crash left in flight, then continue
            # each live session from its restored watermark.
            reattached = list(host._steps.values())
            pump_until(host, lambda: not host.server.has_work()
                       and not host._steps)
            for t in reattached:
                if t.done:
                    note(t)
        else:
            server = server_mod.ScenarioServer(interrupt=interrupt, **kw)
            host = sessions_mod.SessionHost(
                server, lease_s=args.lease_s,
                step_deadline_s=args.deadline_s)
            # Warm the chunk executable BEFORE any lease starts ticking
            # (a cold CPU compile dwarfs interactive TTLs; with a
            # bundle this costs nothing).
            warm = server.submit(queue_mod.ScenarioRequest(
                family=args.family, horizon=chunk_len,
                x0=(0.05, 0.05, 1.0), request_id="warmup"))
            pump_until(host, lambda: warm.done)

        leases = {}
        for sid, (x0, v0, _deltas) in plans.items():
            sess = host.sessions.get(sid)
            if args.resume and sess is not None:
                if sess.status == sessions_mod.LIVE:
                    leases[sid] = sess.lease
                continue  # evicted/closed incarnations stay down.
            grant = host.open(sid, args.family, x0, v0,
                              deadline_s=args.deadline_s)
            if grant["ok"]:
                leases[sid] = grant["lease"]

        # The storm: one step per live client per round, heartbeats
        # between steps, c0 silent past --silent-after.
        for s in range(1, args.steps + 1):
            if state["preempted"]:
                break
            batch = []
            for sid in sorted(leases):
                if (args.silent_after and sid == "c0"
                        and s > args.silent_after):
                    continue
                sess = host.sessions.get(sid)
                if sess is None or sess.status != sessions_mod.LIVE:
                    continue
                if sess.step_seq >= s:
                    continue  # restored watermark already past here.
                dx, dv = plans[sid][2][s - 1]
                batch.append(host.step(sid, leases[sid], s, dx, dv))
            pump_until(host,
                       lambda: all(t.done for t in batch))
            for t in batch:
                if t.done:
                    note(t)
            for sid in sorted(leases):
                if (args.silent_after and sid == "c0"
                        and s > args.silent_after):
                    continue
                if sid in host.sessions and \
                        host.sessions[sid].status == sessions_mod.LIVE:
                    host.heartbeat(sid, leases[sid])

        # Eviction: let c0's lease TTL lapse while the HEALTHY clients
        # keep heartbeating (real wall time — the lease clock is the
        # server's monotonic clock), so the sweep evicts exactly the
        # silent one.
        evicted_ids = []
        if (args.silent_after and not state["preempted"]
                and "c0" in host.sessions
                and host.sessions["c0"].status == sessions_mod.LIVE):
            deadline = time.perf_counter() + 3 * host.lease_s + 1.0
            while (host.sessions["c0"].status == sessions_mod.LIVE
                   and time.perf_counter() < deadline):
                time.sleep(min(0.25, host.lease_s / 4))
                for sid in sorted(leases):
                    if sid == "c0":
                        continue
                    if host.sessions[sid].status == sessions_mod.LIVE:
                        host.heartbeat(sid, leases[sid])
                host.sweep()  # heartbeat() sweeps too; this is a floor.
            evicted_ids = [
                sid for sid, s in host.sessions.items()
                if s.status == sessions_mod.EVICTED
            ]

        if (args.zombie and not state["preempted"]
                and "c0" in host.sessions
                and host.sessions["c0"].status == sessions_mod.EVICTED):
            stale = host.sessions["c0"].lease
            hb = host.heartbeat("c0", stale)
            zs = host.step("c0", stale, 1, (0.0,) * 3, (0.0,) * 3)
            zombie_log = {
                "stale_lease": stale,
                "heartbeat": hb.get("reason"),
                "step": zs.reason,
            }
            note(zs)
            # Reconnect: fresh lease, reset watermark — and it serves.
            x0, v0, deltas = plans["c0"]
            grant = host.open("c0", args.family, x0, v0,
                              deadline_s=args.deadline_s)
            if grant["ok"]:
                leases["c0"] = grant["lease"]
                dx, dv = deltas[0]
                rz = host.step("c0", grant["lease"], 1, dx, dv)
                pump_until(host, lambda: rz.done)
                if rz.done:
                    note(rz)
                zombie_log["reconnect_lease"] = grant["lease"]
                zombie_log["reconnect_rung"] = rz.rung

        # Drain stragglers (degraded steps resolve here too), then
        # close the surviving sessions gracefully — no lease is left to
        # lapse into a spurious eviction during the offline replay.
        if not state["preempted"]:
            pump_until(host, lambda: not host.server.has_work()
                       and not host._steps)
            for sid in sorted(leases):
                sess = host.sessions.get(sid)
                if sess is not None and sess.status == sessions_mod.LIVE:
                    host.close(sid, sess.lease)

        # Lane-independence proof: the served stream equals the offline
        # rollout of the same state stream. Reuses the same server (and
        # executables — zero-compile safe); one-shot requests, distinct
        # batch composition.
        offline = {"checked": 0, "mismatches": []}
        if args.offline_check and not state["preempted"]:
            import numpy as np

            # Group the SERVED step rids by (session, seq): every
            # incarnation replays the same plan from its x0 (open()
            # resets state), so each epoch's step s has the same
            # post-delta state — and each served rid gets checked.
            served_rids: dict[tuple[str, int], list[str]] = {}
            for rid in digests:
                parsed = sessions_mod.parse_step_rid(rid)
                if parsed is not None:
                    sid, _epoch, seq = parsed
                    served_rids.setdefault((sid, seq), []).append(rid)
            checks = {}
            for sid, (x0, v0, deltas) in plans.items():
                x = np.asarray(x0, dtype=np.float64)
                v = np.asarray(v0, dtype=np.float64)
                for s, (dx, dv) in enumerate(deltas, start=1):
                    x = x + np.asarray(dx, dtype=np.float64)
                    v = v + np.asarray(dv, dtype=np.float64)
                    for rid in served_rids.get((sid, s), ()):
                        checks[rid] = server.submit(
                            queue_mod.ScenarioRequest(
                                family=args.family, horizon=chunk_len,
                                x0=tuple(float(val) for val in x),
                                v0=tuple(float(val) for val in v),
                                request_id=f"off.{rid}"))
            pump_until(host,
                       lambda: all(t.done for t in checks.values()))
            for rid, t in checks.items():
                offline["checked"] += 1
                if (t.result is None
                        or result_digest(t.result) != digests[rid]):
                    offline["mismatches"].append(rid)

    # SLO pass (obs/live.py): replay this run's journal through the
    # burn-rate engine and journal fire/resolve transitions back into
    # the SAME metrics file (additive v9 ``alert`` events) so post-hoc
    # readers (run_health) see the alert trail. An alert still firing
    # at end-of-run exits 6 — the nominal ci smoke must stay silent.
    slo_summary = {}
    if args.metrics and os.path.exists(args.metrics):
        from tpu_aerial_transport.obs import export as export_mod

        engine = live_mod.SLOEngine(
            metrics=export_mod.MetricsWriter(args.metrics))
        replica = live_mod.FleetTailer.replica_of(args.metrics)
        for event in export_mod.read_events(args.metrics):
            engine.ingest(replica, event)
        engine.evaluate()
        slo_summary = {
            "slo_firing": sorted(f"{n}/{t}" for n, t in engine.firing),
            "slo_alerts": len(engine.alerts),
        }

    wall_s = time.perf_counter() - t0
    if args.results:
        with open(args.results, "w") as fh:
            json.dump(results, fh, indent=1, sort_keys=True)
    trace_summary = {}
    if tracer is not None and tracer.rows:
        from tpu_aerial_transport.obs import trace as trace_lib

        trace_lib.write_chrome_trace(
            args.trace, trace_lib.stitch(tracer.rows))
        trace_summary = {"trace": args.trace,
                         "trace_spans": len(tracer.rows)}
    sstats = host.stats()
    summary = {
        "mode": ("resume" if args.resume
                 else "bundled" if args.bundle else "jit"),
        "preempted": state["preempted"],
        "wall_s": round(wall_s, 3),
        "pump_rounds": rounds[0],
        "clients": args.clients,
        "steps_per_client": args.steps,
        "evicted_now": evicted_ids,
        **{f"session_{k}": v for k, v in sstats.items()},
        **({"zombie": zombie_log} if zombie_log else {}),
        **({"offline_check": offline} if args.offline_check else {}),
        **trace_summary,
        **slo_summary,
        "hub": hub.snapshot(),
        **counts,
    }
    print(json.dumps(summary), flush=True)
    if args.expect_zero_compile:
        paid = {k: v for k, v in counts.items() if v}
        if paid:
            print(f"serve_sessions: NOT zero-compile: {paid}",
                  file=sys.stderr)
            return 3
    if args.expect_evicted >= 0 and \
            sstats["evicted"] != args.expect_evicted:
        print(f"serve_sessions: evicted {sstats['evicted']} != "
              f"expected {args.expect_evicted}", file=sys.stderr)
        return 4
    if args.expect_fenced >= 0 and \
            sstats["fenced_rejections"] != args.expect_fenced:
        print(f"serve_sessions: fenced {sstats['fenced_rejections']} != "
              f"expected {args.expect_fenced}", file=sys.stderr)
        return 4
    if args.offline_check and offline["mismatches"]:
        print(f"serve_sessions: served stream NOT bitwise equal to "
              f"offline rollout: {offline['mismatches']}",
              file=sys.stderr)
        return 5
    if (args.offline_check and not state["preempted"] and digests
            and offline["checked"] == 0):
        # A check that silently covered nothing is a failed check, not
        # a pass (e.g. the served rid shape drifted from the replay's).
        print("serve_sessions: offline check matched ZERO served steps",
              file=sys.stderr)
        return 5
    if slo_summary.get("slo_firing"):
        print(f"serve_sessions: SLO alerts still firing at end of run: "
              f"{slo_summary['slo_firing']}", file=sys.stderr)
        return 6
    return 0


if __name__ == "__main__":
    sys.exit(main())
