"""Fault-injection demo: an n=4 C-ADMM transport team loses an agent
mid-flight and degrades gracefully.

Runs three rollouts of the same jit-compiled resilient harness —
nominal, one agent killed at t = 1 s, and 30% consensus-message dropout —
and prints a side-by-side summary (tracking error, fallback-ladder rung
counts, quarantine). CPU-friendly:

    JAX_PLATFORMS=cpu python examples/fault_injection.py
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tpu_aerial_transport import resilience
from tpu_aerial_transport.control import cadmm, lowlevel
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience.rollout import resilient_rollout

N = 4
N_HL_STEPS = 200  # 2 s at 100 Hz.


def main():
    params, col, state0 = setup.rqp_setup(N)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=15, inner_iters=20,
    )
    hl = resilience.make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)

    scenarios = {
        "nominal": faults_mod.no_faults(N),
        "agent 0 killed @ t=1s": faults_mod.make_schedule(
            N, t_fail={0: 100}
        ),
        "30% consensus dropout": faults_mod.make_schedule(
            N, drop_rate=0.3, drop_hold=5, key=jax.random.PRNGKey(7)
        ),
    }

    mTg = float(params.mT) * rqp.GRAVITY
    print(f"n={N} agents, payload weight mT*g = {mTg:.2f} N")
    for name, sched in scenarios.items():
        run = jax.jit(lambda s, c, f=sched: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=N_HL_STEPS, faults=f
        ))
        final, _, logs = run(state0, cs0)
        rungs = np.bincount(np.asarray(logs.fallback_rung), minlength=4)
        fz_end = np.asarray(logs.f_des[-1, :, 2])
        print(f"\n== {name} ==")
        print(f"  max |x_err|      : {float(jnp.max(logs.x_err)):.3f} m")
        print(f"  final |x_err|    : {float(logs.x_err[-1]):.3f} m")
        print(f"  final fz per agent [N]: {np.round(fz_end, 2)}")
        print(f"  sum fz / mT g    : {fz_end.sum() / mTg:.3f}")
        print(f"  ladder rungs     : clean={rungs[0]} retry={rungs[1]} "
              f"hold={rungs[2]} equilibrium={rungs[3]}")
        print(f"  quarantined      : {bool(logs.quarantined[-1])}")


if __name__ == "__main__":
    main()
