"""Fault-injection demo: an n=4 C-ADMM transport team loses an agent
mid-flight and degrades gracefully.

Runs three rollouts of the same jit-compiled resilient harness —
nominal, one agent killed at t = 1 s, and 30% consensus-message dropout —
and prints a side-by-side summary (tracking error, fallback-ladder rung
counts, quarantine). CPU-friendly:

    JAX_PLATFORMS=cpu python examples/fault_injection.py

Preemption-safe mode (resilience.recovery): run the killed-agent scenario
as checkpointed chunks — the FULL resilient carry (fallback hold force and
sticky quarantine flag included) is snapshotted at every boundary — then
kill the process and resume it bit-exactly:

    python examples/fault_injection.py --ckpt-dir /tmp/fi1 --chunks 4
    python examples/fault_injection.py --resume /tmp/fi1
"""

from __future__ import annotations

import argparse

import numpy as np

import jax
import jax.numpy as jnp

from tpu_aerial_transport import resilience
from tpu_aerial_transport.control import cadmm, lowlevel
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience.rollout import resilient_rollout

N = 4
N_HL_STEPS = 200  # 2 s at 100 Hz.


def _summarize(name, logs, mTg):
    rungs = np.bincount(
        np.asarray(logs.fallback_rung).reshape(-1), minlength=4
    )
    fz_end = np.asarray(logs.f_des[-1, :, 2])
    print(f"\n== {name} ==")
    print(f"  max |x_err|      : {float(jnp.max(logs.x_err)):.3f} m")
    print(f"  final |x_err|    : {float(logs.x_err[-1]):.3f} m")
    print(f"  final fz per agent [N]: {np.round(fz_end, 2)}")
    print(f"  sum fz / mT g    : {fz_end.sum() / mTg:.3f}")
    print(f"  ladder rungs     : clean={rungs[0]} retry={rungs[1]} "
          f"hold={rungs[2]} equilibrium={rungs[3]}")
    print(f"  quarantined      : {bool(logs.quarantined[-1])}")


def run_checkpointed(ckpt_dir: str, n_chunks: int, resume: bool) -> None:
    """The killed-agent scenario as a chunk-checkpointed resilient rollout:
    `--resume` restores the journaled run (settings come from the journal)
    and continues to the identical final summary."""
    from tpu_aerial_transport.harness import checkpoint
    from tpu_aerial_transport.resilience import recovery
    from tpu_aerial_transport.resilience.rollout import (
        make_chunked_resilient_rollout,
    )

    if resume:
        plan = recovery.read_plan(ckpt_dir)
        n_chunks = plan.n_chunks
        n_hl_steps = plan.n_hl_steps
        t_fail = plan.meta["t_fail"]
        print(f"resuming from {ckpt_dir}: {plan.meta} "
              f"({n_chunks} chunks of {plan.chunk_len} MPC steps)")
    else:
        n_hl_steps = N_HL_STEPS
        t_fail = 100  # agent 0 killed at t = 1 s.

    params, col, state0 = setup.rqp_setup(N)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=15, inner_iters=20,
    )
    hl = resilience.make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)
    sched = faults_mod.make_schedule(N, t_fail={0: t_fail})
    # The hover default of resilient_rollout anchors at the rollout's
    # initial state — under chunking that would re-anchor per chunk, so
    # the reference is pinned to the TRUE initial state explicitly (it is
    # deterministic from setup, hence identical on resume).
    x0 = state0.xl

    def acc_des_fn(state, t):
        del t
        dvl_des = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl_des, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    config_hash = checkpoint.config_fingerprint(
        n=N, t_fail=t_fail, cfg=cfg, n_hl_steps=n_hl_steps
    )
    runner = make_chunked_resilient_rollout(
        hl, ll.control, params, n_hl_steps=n_hl_steps, n_chunks=n_chunks,
        acc_des_fn=acc_des_fn, faults=sched,
    )
    carry0 = runner.init_carry(*jax.tree.map(jnp.copy, (state0, cs0)))
    with recovery.GracefulInterrupt() as interrupt:
        if resume:
            res = recovery.resume_run(
                ckpt_dir, runner.chunk_jit, carry0,
                config_hash=config_hash, interrupt=interrupt,
            )
            print(f"resumed from chunk {res.resumed_from_chunk}")
        else:
            plan = recovery.RunPlan(
                run_dir=ckpt_dir, n_hl_steps=n_hl_steps, n_chunks=n_chunks,
                seed=None, config_hash=config_hash,
                meta={"scenario": "agent 0 killed @ t=1s", "n": N,
                      "t_fail": t_fail},
            )
            res = recovery.run_chunks(
                plan, runner.chunk_jit, carry0, interrupt=interrupt
            )
    if res.status == "preempted":
        raise SystemExit(
            f"preempted at chunk {res.chunks_done}/{n_chunks} — resume "
            f"with: python examples/fault_injection.py --resume {ckpt_dir}"
        )
    mTg = float(params.mT) * rqp.GRAVITY
    _summarize("agent 0 killed @ t=1s (checkpointed)", res.logs, mTg)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--chunks", type=int, default=4, metavar="C",
                   help="chunk count for --ckpt-dir mode")
    p.add_argument("--ckpt-dir", default=None, metavar="DIR",
                   help="run the killed-agent scenario as a checkpointed "
                        "chunked rollout under DIR")
    p.add_argument("--resume", default=None, metavar="DIR",
                   help="resume a --ckpt-dir run from its journal")
    args = p.parse_args()
    if args.resume or args.ckpt_dir:
        run_checkpointed(args.resume or args.ckpt_dir, args.chunks,
                         resume=args.resume is not None)
        return

    params, col, state0 = setup.rqp_setup(N)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=15, inner_iters=20,
    )
    hl = resilience.make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)

    scenarios = {
        "nominal": faults_mod.no_faults(N),
        "agent 0 killed @ t=1s": faults_mod.make_schedule(
            N, t_fail={0: 100}
        ),
        "30% consensus dropout": faults_mod.make_schedule(
            N, drop_rate=0.3, drop_hold=5, key=jax.random.PRNGKey(7)
        ),
    }

    mTg = float(params.mT) * rqp.GRAVITY
    print(f"n={N} agents, payload weight mT*g = {mTg:.2f} N")
    for name, sched in scenarios.items():
        run = jax.jit(lambda s, c, f=sched: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=N_HL_STEPS, faults=f
        ))
        final, _, logs = run(state0, cs0)
        _summarize(name, logs, mTg)


if __name__ == "__main__":
    main()
