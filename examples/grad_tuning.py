"""Gradient-based SO(3) gain tuning through the differentiable simulator.

Demonstrates harness/diff.py: the two-rate cascade (1 kHz low-level SO(3)
attitude control + manifold-integrator physics) is differentiated end-to-end
with ``jax.grad`` (``jax.checkpoint`` rematerialization on the per-step
function), and the attitude PD gains are recovered by projected gradient
descent from a deliberately detuned start. The reference hand-scales these
gains from the Lee-2010 paper values (control/rqp_centralized.py:487-497);
here the simulator tunes them against the rollout objective directly.

Usage: python examples/grad_tuning.py [--steps 40] [--iters 25] [--lr 0.05]
"""

from __future__ import annotations

import argparse


def main() -> None:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over site hooks.
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=3)
    p.add_argument("--steps", type=int, default=40, help="MPC-rate steps")
    p.add_argument("--iters", type=int, default=25, help="SGD iterations")
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from tpu_aerial_transport.control import centralized
    from tpu_aerial_transport.harness import diff, setup
    from tpu_aerial_transport.ops import lie

    params, _, state0 = setup.rqp_setup(args.n)
    f_eq = centralized.equilibrium_forces(params)
    # Tilted initial attitudes + a position step: the attitude loop must
    # actually work, so its gains shape the objective.
    key = jax.random.PRNGKey(0)
    axes = 0.3 * jax.random.normal(key, (args.n, 3))
    state0 = state0.replace(R=jax.vmap(lie.expm_so3)(axes) @ state0.R)
    xl_ref = state0.xl + jnp.array([0.5, 0.0, 0.3])

    loss = diff.make_rollout_loss(
        params, f_eq, xl_ref, n_steps=args.steps, k_att=1.0
    )

    lj = jax.jit(loss)  # one wrapper, one trace cache for all evaluations.
    detuned = {"k_R": jnp.asarray(0.02), "k_Omega": jnp.asarray(0.2)}
    reference = {"k_R": jnp.asarray(0.25), "k_Omega": jnp.asarray(0.075)}
    print(f"loss @ detuned   (k_R=0.02, k_Omega=0.2):   "
          f"{float(lj(detuned, state0)):.5f}")
    print(f"loss @ reference (k_R=0.25, k_Omega=0.075): "
          f"{float(lj(reference, state0)):.5f}")

    gains, hist = diff.tune_gains(
        loss, detuned, state0, lr=args.lr, iters=args.iters
    )
    print(f"tuned gains (best iterate): k_R={float(gains['k_R']):.4f} "
          f"k_Omega={float(gains['k_Omega']):.4f}")
    print("loss history:",
          " ".join(f"{float(v):.5f}" for v in hist[:: max(1, args.iters // 8)]))
    best = float(lj(gains, state0))
    print(f"loss @ tuned gains: {best:.5f} "
          f"(improvement {float(hist[0]) / best:.2f}x over detuned)")


if __name__ == "__main__":
    main()
