#!/usr/bin/env bash
# CI gate: jaxlint (Tier A) + formatting checks over the package.
#
# Exits nonzero on ANY finding. Formatters (black/isort) are optional dev
# deps — when absent the formatting step is SKIPPED with a notice (the
# container image is network-isolated; pip install -e .[dev] where
# available). jaxlint has no dependencies at all and always runs.
#
# tests/test_jaxlint.py invokes this script so tier-1 exercises exactly
# the path CI and humans run.
#
# Usage: tools/ci_check.sh [paths...]   (default: the package + tools)

set -u
cd "$(dirname "$0")/.."

PATHS=("$@")
if [ ${#PATHS[@]} -eq 0 ]; then
    PATHS=(tpu_aerial_transport tools)
fi

fail=0

echo "== jaxlint (Tier A) =="
python tools/jaxlint.py "${PATHS[@]}" || fail=1

echo "== jaxlint --host (Tier C: host-side concurrency/durability/observability) =="
# Stdlib-only like Tier A (never imports jax): clock-domain mixing,
# span leaks, blocking I/O under locks, lock-order cycles, jsonl
# durability bypasses, non-atomic artifact publishes, event-vocabulary
# drift, unregistered env knobs, subprocess hygiene, truthiness gates
# on tracer/metrics params (the ISSUE-17 rules, HL001-HL010). Scans
# its own fixed host-side tree (serving/, resilience/, obs/,
# parallel/pods.py, tools/), so no paths are passed. Waivers are
# per-site with written reasons in analysis/hostrules.py:HOST_WAIVERS;
# stale or unreasoned waivers fail (HL000).
python tools/jaxlint.py --host || fail=1

echo "== jaxlint --contracts --target tpu (ring + fused-kernel + effort + env-query + lane-surgery entrypoints) =="
# TC106 off-chip TPU lowering gate + Tier-B trace contracts over the
# ring-exchange entrypoints (PR 7), the whole-solve fused-ADMM kernel
# entrypoints (PR 12: ops.admm_kernel:fused_solve_{interpret,pallas} —
# the pallas entry's TC106 run is what catches a jax upgrade breaking
# the compiled form's Mosaic lowering on a CPU box instead of at the
# chip round), and the adaptive-effort entrypoints (PR 13:
# ops.admm_kernel:fused_solve_earlyexit_{interpret,pallas} — the
# in-kernel early-exit scf.while form — plus the adaptive consensus
# steps control.{cadmm,dd}:control_adaptive, and the bucketed
# environment-query tier (envs.spatial:env_query_{bucketed,dense} —
# the candidate-slab gather + shared sweep math must keep TPU-target
# lowering clean off-chip, no waiver), and the serving boundary
# lane-surgery entrypoints (ISSUE 18:
# serving.lanes:lane_surgery{,_centralized} — the donated on-device
# select program must keep TC105 aliasing and TPU lowering clean so
# device-resident batching can flip on without a chip round). The ring
# entries need a
# >=4-device mesh, so force a virtual-device CPU host through the ONE
# shared knob (utils/platform.py TAT_VIRTUAL_DEVICES; default 4 here) —
# min_devices/waived entries silently skip on 1-device boxes otherwise —
# and the gate is designed to run off-chip (JAX_PLATFORMS=cpu even on a
# TPU box). The full registry runs under `tools/jaxlint.py --contracts`
# / -m slow.
JAX_PLATFORMS=cpu \
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${TAT_VIRTUAL_DEVICES:-4}" \
python tools/jaxlint.py --contracts --target tpu \
    --only parallel.ring:consensus_exchange,parallel.ring:consensus_exchange_pallas,parallel.mesh:cadmm_control_sharded_ring,ops.admm_kernel:fused_solve_interpret,ops.admm_kernel:fused_solve_pallas,ops.admm_kernel:fused_solve_earlyexit_interpret,ops.admm_kernel:fused_solve_earlyexit_pallas,control.cadmm:control_adaptive,control.dd:control_adaptive,envs.spatial:env_query_bucketed,envs.spatial:env_query_dense,serving.lanes:lane_surgery,serving.lanes:lane_surgery_centralized \
    tpu_aerial_transport/parallel/ring.py tpu_aerial_transport/ops/admm_kernel.py tpu_aerial_transport/control/cadmm.py tpu_aerial_transport/control/dd.py tpu_aerial_transport/envs/spatial.py tpu_aerial_transport/serving/lanes.py || fail=1

echo "== pods 2-process parity smoke (tools/pods_local.py) =="
# Bounded multi-process smoke of the pods tier (parallel/pods.py): 2
# REAL processes x 2 virtual CPU devices each, gloo cross-process
# collectives, compared by the harness against the single-process run
# of the SAME 2x2 mesh (--check-parity; f32-rounding bar). Workers are
# group-killable under the harness deadline and watch their parent pid
# (no orphaned gloo rendezvous); a 1-core host skips with a written
# reason (the harness prints it and exits 0). The heavier masked /
# 2x4-acceptance / 1024-agent e2es live in tests/test_pods.py (-m slow).
python tools/pods_local.py --mode parity --check-parity \
    --processes 2 --local-devices 2 --n 4 --scenarios 4 --steps 1 \
    --max-iter 2 --no-masked --out-dir artifacts/pods-smoke \
    --timeout 420 || fail=1

echo "== serving fleet 2-replica smoke (tools/fleet_local.py) =="
# Bounded fleet smoke (ISSUE 16, serving/fleet.py): 2 REAL replica
# worker processes behind the consistent-hash admission front, a small
# fault-free request batch, every ticket resolved. Replicas follow the
# pods_local discipline (own session, group-killable, parent-pid
# watchdog); a 1-core host skips with a written reason (the harness
# prints the skip JSON and exits 0 — replicas are independent CPU
# processes, but time-slicing 2 jax boots through one core blows the
# smoke budget). The chaos-storm e2es live in tests/test_fleet.py
# (-m slow).
python tools/fleet_local.py --replicas 2 --requests 6 \
    --out-dir artifacts/fleet-smoke --timeout 420 || fail=1

echo "== closed-loop session smoke (examples/serve_sessions.py) =="
# Bounded session-tier smoke (serving/sessions.py): one in-process
# replica, 4 leased sessions streaming 2 steps each, one silent client
# evicted at lease expiry (healthy clients heartbeat through the wait),
# its zombie retry fenced twice (heartbeat + step), reconnect served,
# and every served step's digest proven bitwise equal to the offline
# one-shot replay of the same state stream. Exit 4 on the wrong
# evict/fence counts, 5 on any digest mismatch. The deadline-storm /
# SIGTERM-resume / failover acceptance e2es live in
# tests/test_sessions.py.
mkdir -p artifacts/session-smoke
JAX_PLATFORMS=cpu python examples/serve_sessions.py \
    --clients 4 --steps 2 --lease-s 2.0 --silent-after 1 --zombie \
    --offline-check --expect-evicted 1 --expect-fenced 2 \
    --metrics artifacts/session-smoke/sessions.metrics.jsonl \
    --results artifacts/session-smoke/results.json || fail=1
python tools/run_health.py --validate \
    artifacts/session-smoke/sessions.metrics.jsonl || fail=1

echo "== fleet console one-shot (tools/fleet_console.py --once) =="
# Live-SLO console over the session smoke's journal (obs/live.py): the
# tailer must drain the file, the rolling windows must aggregate it,
# and the burn-rate engine must evaluate CLEAN — the nominal smoke
# fires no alerts, and --once exits nonzero when any alert is left
# firing, so this line doubles as the nominal-alerting gate.
python tools/fleet_console.py --once \
    artifacts/session-smoke/sessions.metrics.jsonl || fail=1

echo "== aot bundle coverage (tools/aot_bundle.py check) =="
# Registry/bundle drift gate (PR 8): the in-tree manifest-only coverage
# record must keep matching the live entrypoint registry — a new/changed
# entrypoint cannot land without rebuilding it (python tools/aot_bundle.py
# build --out artifacts/aot/coverage-cpu --manifest-only --platform cpu,
# under the same forced 8-virtual-device CPU env used here: sharded
# entries' arg shapes depend on the device count). Signatures come from
# make_args avals only — the gate never lowers or compiles anything.
# Deliberately PINNED at 8 (not TAT_VIRTUAL_DEVICES): the tracked
# coverage record was built at 8 and an env override must not make the
# diff lie.
if [ -f artifacts/aot/coverage-cpu/manifest.json ]; then
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python tools/aot_bundle.py check artifacts/aot/coverage-cpu \
        --manifest-hint || fail=1
else
    echo "artifacts/aot/coverage-cpu/manifest.json MISSING (tracked file)"
    fail=1
fi

echo "== metrics jsonl schema (obs.export) =="
shopt -s nullglob
metrics_files=(artifacts/*.metrics.jsonl)
shopt -u nullglob
if [ ${#metrics_files[@]} -gt 0 ]; then
    python tools/run_health.py --validate "${metrics_files[@]}" || fail=1
else
    echo "no artifacts/*.metrics.jsonl — skipped"
fi

echo "== trace-event json (tools/trace_view.py --validate) =="
# Distributed-tracing artifacts (ISSUE 15): any emitted Perfetto trace
# must be well-formed trace-event JSON with per-track monotone,
# non-overlapping slices and every span's parent present in the file —
# the structural contract chrome://tracing / ui.perfetto.dev rely on.
# trace_view loads the span layer by file path (no jax import).
shopt -s nullglob
trace_files=(artifacts/*.trace.json)
shopt -u nullglob
if [ ${#trace_files[@]} -gt 0 ]; then
    python tools/trace_view.py --validate "${trace_files[@]}" || fail=1
else
    echo "no artifacts/*.trace.json — skipped"
fi

echo "== black --check =="
if python -c "import black" 2>/dev/null; then
    python -m black --check --quiet "${PATHS[@]}" || fail=1
else
    echo "black not installed — skipped (pip install -e .[dev])"
fi

echo "== isort --check =="
if python -c "import isort" 2>/dev/null; then
    python -m isort --check-only --quiet "${PATHS[@]}" || fail=1
else
    echo "isort not installed — skipped (pip install -e .[dev])"
fi

if [ "$fail" -ne 0 ]; then
    echo "ci_check: FAILED"
    exit 1
fi
echo "ci_check: OK"
