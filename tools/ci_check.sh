#!/usr/bin/env bash
# CI gate: jaxlint (Tier A) + formatting checks over the package.
#
# Exits nonzero on ANY finding. Formatters (black/isort) are optional dev
# deps — when absent the formatting step is SKIPPED with a notice (the
# container image is network-isolated; pip install -e .[dev] where
# available). jaxlint has no dependencies at all and always runs.
#
# tests/test_jaxlint.py invokes this script so tier-1 exercises exactly
# the path CI and humans run.
#
# Usage: tools/ci_check.sh [paths...]   (default: the package + tools)

set -u
cd "$(dirname "$0")/.."

PATHS=("$@")
if [ ${#PATHS[@]} -eq 0 ]; then
    PATHS=(tpu_aerial_transport tools)
fi

fail=0

echo "== jaxlint (Tier A) =="
python tools/jaxlint.py "${PATHS[@]}" || fail=1

echo "== jaxlint --contracts --target tpu (ring consensus entrypoints) =="
# TC106 off-chip TPU lowering gate + Tier-B trace contracts over the
# ring-exchange entrypoints (PR 7). The ring entries need a >=4-device
# mesh, so force a 4-virtual-device CPU host — the gate is designed to
# run off-chip (JAX_PLATFORMS=cpu even on a TPU box). The full registry
# runs under `tools/jaxlint.py --contracts` / -m slow.
JAX_PLATFORMS=cpu \
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4" \
python tools/jaxlint.py --contracts --target tpu \
    --only parallel.ring:consensus_exchange,parallel.ring:consensus_exchange_pallas,parallel.mesh:cadmm_control_sharded_ring \
    tpu_aerial_transport/parallel/ring.py || fail=1

echo "== aot bundle coverage (tools/aot_bundle.py check) =="
# Registry/bundle drift gate (PR 8): the in-tree manifest-only coverage
# record must keep matching the live entrypoint registry — a new/changed
# entrypoint cannot land without rebuilding it (python tools/aot_bundle.py
# build --out artifacts/aot/coverage-cpu --manifest-only --platform cpu,
# under the same forced 8-virtual-device CPU env used here: sharded
# entries' arg shapes depend on the device count). Signatures come from
# make_args avals only — the gate never lowers or compiles anything.
if [ -f artifacts/aot/coverage-cpu/manifest.json ]; then
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python tools/aot_bundle.py check artifacts/aot/coverage-cpu \
        --manifest-hint || fail=1
else
    echo "artifacts/aot/coverage-cpu/manifest.json MISSING (tracked file)"
    fail=1
fi

echo "== metrics jsonl schema (obs.export) =="
shopt -s nullglob
metrics_files=(artifacts/*.metrics.jsonl)
shopt -u nullglob
if [ ${#metrics_files[@]} -gt 0 ]; then
    python tools/run_health.py --validate "${metrics_files[@]}" || fail=1
else
    echo "no artifacts/*.metrics.jsonl — skipped"
fi

echo "== black --check =="
if python -c "import black" 2>/dev/null; then
    python -m black --check --quiet "${PATHS[@]}" || fail=1
else
    echo "black not installed — skipped (pip install -e .[dev])"
fi

echo "== isort --check =="
if python -c "import isort" 2>/dev/null; then
    python -m isort --check-only --quiet "${PATHS[@]}" || fail=1
else
    echo "isort not installed — skipped (pip install -e .[dev])"
fi

if [ "$fail" -ne 0 ]; then
    echo "ci_check: FAILED"
    exit 1
fi
echo "ci_check: OK"
