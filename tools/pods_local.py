#!/usr/bin/env python
"""Localhost pods harness: coordinator + N group-killable worker
processes on the CPU backend (the virtual-device trick the ring parity
tests use, one process per "host"), driving the 2-D pods-mesh tier
(tpu_aerial_transport/parallel/pods.py) without a chip.

This is the off-chip proof path for the 1024-agent BASELINE config: the
same ``jax.distributed`` bootstrap, the same 2-D ``(scenario, agent)``
mesh, the same gloo cross-process collectives a CPU pod would use — so
multi-process bugs (wrong mesh layout, non-replicated host values,
collectives crossing the process boundary they shouldn't) surface here
instead of on a booked v4-32.

Modes (parent prints ONE final JSON line from worker 0):

- ``parity``: run ``pods.parity_digest`` (deterministic rollout + masked
  control step) and dump the host-global digest npz to ``--out-dir`` —
  tests/test_pods.py and tools/ci_check.sh compare it against a
  single-process run of the SAME digest to f32 rounding.
- ``bench``: timed weak-scaling cell — compile+warm, then median-of-reps
  rollout rate; the JSON carries ``scenario_mpc_steps_per_sec``,
  ``compile_wall_s``, and the full topology (``bench.py --sweep``'s
  ``pods_*`` cells drive this).
- ``resume``: chunked pods run with per-process snapshot shards;
  ``--stop-after-chunk K`` simulates preemption at boundary K (the
  journal-driven interrupt below), ``--resume`` completes it — the slow
  e2e asserts the two-invocation digest equals the uninterrupted one.

Every worker runs in its OWN session; on deadline the parent SIGKILLs
each worker's whole process group (the ``resilience.backend.run_group``
discipline — a wedged gloo rendezvous must not orphan workers holding
the rendezvous port). Hosts that cannot run multiple workers (1 CPU
core) skip with a written reason instead of flaking.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RESULT_TAG = "PODS_RESULT "


# ----------------------------------------------------------------------
# Worker side.
# ----------------------------------------------------------------------

def _worker_env_setup(args) -> None:
    """Backend config that must precede ANY jax device use: CPU platform,
    the shared virtual-device knob, the persistent compile cache, then
    the distributed bootstrap."""
    os.environ.setdefault("JAX_PLATFORMS", args.platform)
    from tpu_aerial_transport.utils.platform import (
        apply_virtual_devices,
        enable_persistent_cache,
        honor_jax_platforms_env,
    )

    apply_virtual_devices(default=args.local_devices)
    honor_jax_platforms_env()
    enable_persistent_cache()
    from tpu_aerial_transport.parallel import pods

    pods.initialize()  # TAT_PODS_* env from the parent; no-op when solo.


def _simulated_preemption(plan, stop_after: int):
    """An ``interrupt`` duck-type for recovery.run_chunks that trips at a
    DETERMINISTIC boundary: triggered once the per-process journal shows
    ``stop_after`` completed chunks. Pure public surfaces — the driver
    checks ``interrupt.triggered`` at each boundary, the journal is the
    durable chunk record — so the "crash" lands at exactly the same
    boundary on every process and every run."""
    from tpu_aerial_transport.resilience.recovery import RunJournal

    journal = RunJournal(plan.run_dir, filename=plan.journal_filename)

    class _Trip:
        @property
        def triggered(self):
            done = len(journal.completed_chunks())
            return "SIMULATED_PREEMPT" if done >= stop_after else None

    return _Trip()


def _orphan_watchdog() -> None:
    """Workers run in their OWN sessions (group-killability), so killing
    the parent's group does NOT reap them — a bench-side deadline kill of
    the harness would leak N workers holding the gloo rendezvous port.
    Each worker therefore watches its parent pid and exits the moment it
    is reparented (orphaned)."""
    import threading

    parent = os.getppid()

    def watch():
        while True:
            time.sleep(2.0)
            if os.getppid() != parent:
                os._exit(17)

    threading.Thread(target=watch, daemon=True).start()


def run_worker(args) -> int:
    _orphan_watchdog()
    _worker_env_setup(args)
    import jax
    import numpy as np

    from tpu_aerial_transport.parallel import mesh as mesh_mod
    from tpu_aerial_transport.parallel import pods

    spec = pods.resolve_pods_spec(
        args.n, args.mesh or "auto",
        n_devices=args.processes * args.local_devices,
        n_processes=args.processes,
    )
    pods.check_topology(spec)  # classified topology_mismatch on shortfall.
    mesh = pods.make_pods_mesh(spec)
    pid = jax.process_index()
    out: dict = {
        "mode": args.mode,
        "n_processes": spec.n_processes,
        "n_devices": spec.n_devices,
        "mesh": {"scenario": spec.scenario_shards,
                 "agent": spec.agent_shards},
        "n": args.n,
        "n_scenarios": args.scenarios,
        "agents_total": args.n * args.scenarios,
    }

    if args.mode == "parity":
        digest = pods.parity_digest(
            mesh, n=args.n, n_scenarios=args.scenarios,
            n_steps=args.steps, max_iter=args.max_iter,
            controller=args.controller, masked=args.masked,
        )
        if pid == 0 and args.out_dir:
            os.makedirs(args.out_dir, exist_ok=True)
            np.savez(
                os.path.join(args.out_dir, "parity.npz"),
                **{k: np.asarray(v) for k, v in digest.items()},
            )
        out["digest_sums"] = {
            k: float(np.abs(np.asarray(v)).sum()) for k, v in digest.items()
        }
        out["ok"] = bool(all(
            np.isfinite(np.asarray(v)).all() for v in digest.values()
        ))

    elif args.mode == "bench":
        roll, init_batch = pods.make_pods_workload(
            args.n, mesh, controller=args.controller,
            max_iter=args.max_iter,
        )
        css, states = init_batch(args.scenarios)
        css = mesh_mod.shard_scenarios(mesh, css)
        states = mesh_mod.shard_scenarios(mesh, states)
        t0 = time.perf_counter()
        o = roll(css, states, n_steps=args.steps)
        jax.block_until_ready(jax.tree.leaves(o)[0])
        compile_wall_s = time.perf_counter() - t0
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            o = roll(css, states, n_steps=args.steps)
            jax.block_until_ready(jax.tree.leaves(o)[0])
            times.append(time.perf_counter() - t0)
        wall = float(np.median(times))
        out.update(
            scenario_mpc_steps_per_sec=args.scenarios * args.steps / wall,
            agent_mpc_steps_per_sec=(
                args.scenarios * args.steps / wall * args.n
            ),
            compile_wall_s=round(compile_wall_s, 2),
            steps=args.steps,
            ok=bool(np.isfinite(np.asarray(o[2])).all()),
        )

    elif args.mode == "resume":
        from tpu_aerial_transport.harness import rollout as h_rollout

        # The resumable tier is scenario-data-parallel (the PR-4 chunked
        # rollout vmapped over the pods mesh); each process feeds its
        # LOCAL slab and snapshots only it.
        params, cfg, llc, hl, acc_des_fn = _centralized_bits(args.n)
        runner = h_rollout.make_chunked_rollout(
            hl, llc.control, params, n_hl_steps=args.steps,
            n_chunks=args.chunks, hl_rel_freq=2, acc_des_fn=acc_des_fn,
        )
        run = pods.pods_rollout_resumable(
            runner.chunk_fn, mesh,
            n_hl_steps=args.steps, n_chunks=args.chunks,
            run_dir=args.out_dir, seed=0,
            # tracer=True: per-process span track (p{pid}ofN) into a
            # per-process trace jsonl in the shared run dir — the
            # parent stitches them (tools/trace_view.py machinery)
            # into ONE Perfetto trace after the pod exits. None (not
            # False!) when untraced: the chunk driver's zero-cost gate
            # is `tracer is not None`.
            tracer=(True if args.trace else None),
        )
        local = _local_resume_carry(args, spec, params, cfg, runner)
        interrupt = None
        if args.stop_after_chunk is not None:
            interrupt = _simulated_preemption(
                run.plan, args.stop_after_chunk
            )
        result = run(local, resume=args.resume, interrupt=interrupt)
        final_local = pods.local_host_shard(result.carry)
        xl = np.asarray(jax.tree.leaves(final_local)[0])
        out.update(
            status=result.status, chunks_done=result.chunks_done,
            resumed_from_chunk=result.resumed_from_chunk,
            digest=float(np.abs(xl).sum()),
            xl0=[float(v) for v in np.asarray(
                final_local[0].xl
            ).reshape(-1)[:3]],
            ok=result.status in ("done", "preempted"),
        )

    else:
        raise SystemExit(f"unknown mode {args.mode}")

    if pid == 0:
        print(RESULT_TAG + json.dumps(out), flush=True)
    return 0


def _centralized_bits(n):
    """Centralized-controller rollout pieces for the resume mode (the
    scenario_rollout_resumable workload shape: cheap per-lane program,
    the multi-process machinery is what's under test)."""
    import jax.numpy as jnp

    from tpu_aerial_transport.control import centralized, lowlevel
    from tpu_aerial_transport.harness import setup

    params, col, _state = setup.rqp_setup(n)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=8
    )
    f_eq = centralized.equilibrium_forces(params)
    llc = lowlevel.make_lowlevel_controller("pd", params)
    anchor = jnp.array([5.0, 0.0, 2.0], jnp.float32)

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    def acc_des_fn(state, t):
        # Fixed global anchor (the batch center): chunk-offset-invariant,
        # so chunked == fused stays bitwise (the make_chunked_rollout
        # acc_des_fn contract).
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - anchor)
        return (dvl, jnp.zeros(3, state.xl.dtype)), anchor, jnp.zeros(3)

    return params, cfg, llc, hl, acc_des_fn


def _local_resume_carry(args, spec, params, cfg, runner):
    """This process's slab of the deterministic global initial carry."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_aerial_transport.control import centralized
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.parallel import pods

    _p, _c, state0 = setup.rqp_setup(args.n)
    states = pods.scenario_batch(state0, args.scenarios)
    cs0 = centralized.init_ctrl_state(params, cfg)
    css = jax.vmap(lambda _: cs0)(jnp.arange(args.scenarios))
    carry = jax.vmap(runner.init_carry)(states, css)
    pid = jax.process_index()
    rows = args.scenarios // spec.n_processes
    return jax.tree.map(
        lambda x: np.array(np.asarray(x)[pid * rows:(pid + 1) * rows],
                           copy=True),
        carry,
    )


# ----------------------------------------------------------------------
# Parent side.
# ----------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _strip_force_flag(flags: str) -> str:
    """Drop any ambient --xla_force_host_platform_device_count pin so the
    workers' TAT_VIRTUAL_DEVICES request (utils/platform.py) applies —
    the parent may itself run under the test conftest's 8-device pin."""
    return " ".join(
        tok for tok in flags.split()
        if not tok.startswith("--xla_force_host_platform_device_count")
    ).strip()


def spawn_pod(args, extra_worker_args: list[str] | None = None):
    """Spawn the N workers (each in its own session) and babysit them
    under one deadline. Returns ``(result_dict | None, rc, tail)``."""
    from tpu_aerial_transport.resilience.backend import (
        EXPECTED_DEVICES_ENV,
        EXPECTED_PROCESSES_ENV,
    )
    from tpu_aerial_transport.utils.platform import VIRTUAL_DEVICES_ENV

    port = _free_port()
    workers = []
    base_env = dict(os.environ)
    base_env["XLA_FLAGS"] = _strip_force_flag(
        base_env.get("XLA_FLAGS", "")
    )
    base_env.update({
        "JAX_PLATFORMS": args.platform,
        VIRTUAL_DEVICES_ENV: str(args.local_devices),
        "TAT_PODS_COORDINATOR": f"127.0.0.1:{port}",
        "TAT_PODS_NUM_PROCESSES": str(args.processes),
        EXPECTED_DEVICES_ENV: str(args.processes * args.local_devices),
        EXPECTED_PROCESSES_ENV: str(args.processes),
    })
    cmd_base = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--mode", args.mode, "--processes", str(args.processes),
        "--local-devices", str(args.local_devices),
        "--n", str(args.n), "--scenarios", str(args.scenarios),
        "--steps", str(args.steps), "--max-iter", str(args.max_iter),
        "--reps", str(args.reps), "--chunks", str(args.chunks),
        "--controller", args.controller, "--platform", args.platform,
    ] + (["--mesh", args.mesh] if args.mesh else []) \
      + (["--out-dir", args.out_dir] if args.out_dir else []) \
      + (["--trace", args.trace] if args.trace else []) \
      + (["--resume"] if args.resume else []) \
      + ([] if args.masked else ["--no-masked"]) \
      + (["--stop-after-chunk", str(args.stop_after_chunk)]
         if args.stop_after_chunk is not None else []) \
      + (extra_worker_args or [])
    for pid in range(args.processes):
        env = dict(base_env)
        env["TAT_PODS_PROCESS_ID"] = str(pid)
        workers.append(subprocess.Popen(
            cmd_base, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, start_new_session=True, cwd=_REPO,
        ))

    # Drain every worker's pipes CONCURRENTLY: a sequential
    # communicate() on worker 0 first would deadlock the pod if another
    # worker fills its pipe buffer (64 KB of XLA/gloo log spew) while
    # worker 0 blocks in a collective waiting on it — and on timeout the
    # sequential path would discard the very output that says why.
    import threading

    outs: list = [("", "")] * len(workers)

    def _drain(i, w):
        outs[i] = w.communicate()

    threads = [
        threading.Thread(target=_drain, args=(i, w), daemon=True)
        for i, w in enumerate(workers)
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    if any(t.is_alive() for t in threads):
        for w in workers:
            try:
                os.killpg(w.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                w.kill()
        for t in threads:
            t.join(10.0)  # the kill unblocks communicate(); collect tails.
        tails = " ;; ".join(
            f"worker{i}: " + " | ".join(
                (e or o or "").strip().splitlines()[-2:]
            )
            for i, (o, e) in enumerate(outs)
        )
        return None, 124, (
            f"deadline {args.timeout:g}s exceeded (pod group-killed; "
            f"gloo rendezvous wedged?) ;; {tails}"
        )

    rcs = [w.returncode for w in workers]
    result = None
    for line in (outs[0][0] or "").splitlines():
        if line.startswith(RESULT_TAG):
            try:
                result = json.loads(line[len(RESULT_TAG):])
            except ValueError:
                pass
    if any(rcs) or result is None:
        tails = []
        for i, (o, e) in enumerate(outs):
            tail = (e or o or "").strip().splitlines()[-4:]
            tails.append(f"worker{i} rc={rcs[i]}: " + " | ".join(tail))
        return result, max(rcs) or 1, " ;; ".join(tails)
    return result, 0, ""


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one pod process.
    ap.add_argument("--mode", default="parity",
                    choices=["parity", "bench", "resume"])
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=4)
    ap.add_argument("--mesh", default="",
                    help="SxA force (default: pods auto resolution / "
                         "TAT_PODS_MESH)")
    ap.add_argument("--n", type=int, default=8, help="agents per scenario")
    ap.add_argument("--scenarios", type=int, default=8)
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--max-iter", type=int, default=4)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--chunks", type=int, default=4,
                    help="resume mode: chunk count")
    ap.add_argument("--controller", default="cadmm",
                    choices=["cadmm", "dd"])
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--out-dir", default="")
    ap.add_argument("--trace", default="",
                    help="resume mode: write a stitched cross-process "
                         "Chrome/Perfetto trace to this path (each "
                         "worker records spans on its own p{pid}ofN "
                         "track into the shared run dir; the parent "
                         "aligns the per-process monotonic clocks and "
                         "emits ONE trace)")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--resume", action="store_true",
                    help="resume mode: continue a preempted run_dir")
    ap.add_argument("--stop-after-chunk", type=int, default=None,
                    help="resume mode: simulate preemption at boundary K")
    ap.add_argument("--masked", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="parity mode: include the alive-masked/fault-"
                         "injected control step (--no-masked: cheaper "
                         "smoke)")
    ap.add_argument("--check-parity", action="store_true",
                    help="parity mode: ALSO run the single-process "
                         "reference pod and compare the two digests to "
                         "f32 rounding (exit 1 on mismatch) — the "
                         "self-contained ci_check smoke")
    args = ap.parse_args()

    if args.worker:
        return run_worker(args)

    if (os.cpu_count() or 1) < 2 and args.processes > 1:
        # The written skip reason the ci gate and the sweep record keep:
        # N gloo workers time-slicing ONE core wedge the rendezvous more
        # often than they finish.
        print(json.dumps({
            "skipped": f"1-core host (os.cpu_count()={os.cpu_count()}): "
                       f"cannot run {args.processes} pod workers reliably",
        }), flush=True)
        return 0

    if args.mode == "parity" and args.check_parity:
        return check_parity(args)
    if args.trace and (args.mode != "resume" or not args.out_dir):
        raise SystemExit("--trace needs --mode resume and --out-dir "
                         "(the traced chunk driver + the shared run dir "
                         "the stitcher reads)")
    result, rc, tail = spawn_pod(args)
    if rc:
        print(json.dumps({
            "error": tail, "rc": rc, "mode": args.mode,
        }), flush=True)
        return rc
    if args.trace:
        result["trace"] = stitch_trace(args.out_dir, args.trace)
    print(json.dumps(result), flush=True)
    return 0


def stitch_trace(run_dir: str, out_path: str) -> dict:
    """Parent-side stitch: every worker's per-process trace jsonl in the
    shared run dir onto one clock, emitted as Perfetto trace JSON. The
    shard manifest in the run dir names how many process tracks make the
    trace complete — a partial stitch (including ZERO spans from
    deadline-killed workers) raises rather than publishing a trace that
    silently dropped a worker. The span layer comes via trace_view's
    by-path loader — ONE copy of that loading discipline."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import trace_view

    trace_mod = trace_view.trace_mod
    rows = trace_mod.stitch_run_dir(run_dir)
    obj = trace_mod.write_chrome_trace(out_path, rows)
    return {
        "path": out_path,
        "spans": len(rows),
        "tracks": sorted({r.get("track") for r in rows}),
        "events": len(obj["traceEvents"]),
    }


# Parity bar: the two topologies run the SAME program over the SAME mesh
# shape; only the cross-process exchange's f32 summation order differs
# (test_ring's full-control-step tolerance).
PARITY_ATOL = 2e-3


def check_parity(args) -> int:
    """Run the multi-process pod AND the single-process reference pod
    (same ``SxA`` mesh, all devices in one process), then compare their
    digests — the self-contained parity smoke ci_check runs."""
    import numpy as np

    out_multi = os.path.join(args.out_dir or "artifacts/pods-smoke",
                             "multi")
    out_single = os.path.join(args.out_dir or "artifacts/pods-smoke",
                              "single")
    runs = []
    for procs, local, out in (
        (args.processes, args.local_devices, out_multi),
        (1, args.processes * args.local_devices, out_single),
    ):
        sub = argparse.Namespace(**vars(args))
        sub.processes, sub.local_devices, sub.out_dir = procs, local, out
        if not sub.mesh:
            # Pin the SAME mesh shape on both arms (auto would resolve
            # differently for different process counts).
            sub.mesh = (f"{args.processes * args.local_devices // _agents_div(args)}"
                        f"x{_agents_div(args)}")
        result, rc, tail = spawn_pod(sub)
        if rc:
            print(json.dumps({
                "error": tail, "rc": rc, "mode": "parity-check",
                "processes": procs,
            }), flush=True)
            return rc
        runs.append(result)

    a = np.load(os.path.join(out_multi, "parity.npz"))
    b = np.load(os.path.join(out_single, "parity.npz"))
    diffs = {k: float(np.abs(a[k] - b[k]).max()) for k in a.files}
    ok = set(a.files) == set(b.files) and all(
        d <= PARITY_ATOL for d in diffs.values()
    )
    print(json.dumps({
        "mode": "parity-check", "parity_ok": ok, "atol": PARITY_ATOL,
        "max_diffs": diffs,
        "multi": runs[0].get("mesh"), "single": runs[1].get("mesh"),
        "n_processes": args.processes,
    }), flush=True)
    return 0 if ok else 1


def _agents_div(args) -> int:
    """Largest agent-shard count dividing both n and the per-process
    device count (the pods auto rule, parent-side — no jax import)."""
    return max(
        d for d in range(1, min(args.local_devices, args.n) + 1)
        if args.n % d == 0 and args.local_devices % d == 0
    )


if __name__ == "__main__":
    raise SystemExit(main())
