#!/usr/bin/env python
"""Run-health summary from a flight-recorder metrics jsonl.

Renders the schema-versioned event log written by ``obs.export``
(``resilience.recovery.run_chunks(metrics=...)`` chunk boundaries,
``bench.py --sweep`` cells, on-demand ``obs.export.rollout_metrics``)
as operator-facing tables: fallback-rung distribution, consensus-residual
percentiles, safety-margin minima, chunk wall-times, and
resume/retry/preemption events — "is this fleet healthy and where is the
time going" without re-running the workload.

Usage:
  python tools/run_health.py RUN.metrics.jsonl [--json]
  python tools/run_health.py --validate artifacts/*.metrics.jsonl
  python tools/run_health.py artifacts/fleet/ --follow --window 60

``--validate`` only schema-checks the files (the ``tools/ci_check.sh``
gate); exit 1 on any violation. ``--follow`` switches to the live
tailer (``obs.live``): paths may be directories scanned for
``*.metrics.jsonl``, and one rolling per-tenant rate table over the
trailing ``--window`` seconds redraws every refresh
(TAT_CONSOLE_REFRESH_S) — ``tools/fleet_console.py`` is the full
multi-window + SLO view; this is the single-window vitals line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpu_aerial_transport.obs import export as export_mod  # noqa: E402
from tpu_aerial_transport.obs import live as live_mod  # noqa: E402
from tpu_aerial_transport.obs import trace as trace_lib  # noqa: E402

RUNG_LABELS = ("0 clean", "1 retry", "2 hold", "3 equilibrium")


def _percentile(xs: list[float], p: float) -> float:
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, round(p * (len(xs) - 1))))
    return xs[k]


def summarize(events: list[dict]) -> dict:
    """Aggregate a run's events into the summary dict the tables render."""
    chunks = [e for e in events if e.get("event") == "chunk"]
    out: dict = {
        "events": {},
        "run_start": next(
            (e for e in events if e.get("event") == "run_start"), None
        ),
    }
    for e in events:
        k = e.get("event", "?")
        out["events"][k] = out["events"].get(k, 0) + 1

    # The telemetry accumulator is cumulative: the LAST chunk's telemetry
    # block is the whole-run summary. rollout_summary events carry their
    # own exact digests.
    tel = next(
        (e["telemetry"] for e in reversed(chunks)
         if e.get("telemetry")), None,
    )
    if tel is None:
        tel = next(
            (e["telemetry"] for e in reversed(events)
             if e.get("event") == "rollout_summary" and e.get("telemetry")),
            None,
        )
    out["telemetry"] = tel

    # Exact per-chunk / rollout log digests, summed.
    digests = [e["logs"] for e in chunks if e.get("logs")] + [
        e["logs"] for e in events
        if e.get("event") == "rollout_summary" and e.get("logs")
    ]
    if digests:
        agg = {
            "steps": sum(d["steps"] for d in digests),
            "rung_hist": [
                sum(d["rung_hist"][i] for d in digests) for i in range(4)
            ],
            "min_env_dist": min(d["min_env_dist"] for d in digests),
            "collision_steps": sum(d["collision_steps"] for d in digests),
            "quarantined_final": digests[-1].get("quarantined_final", 0),
            "residual_max": max(
                (d["residual"]["max"] for d in digests
                 if d["residual"].get("max") is not None),
                default=None,
            ),
        }
        out["logs"] = agg

    if chunks:
        walls = [e["wall_s"] for e in chunks]
        out["chunks"] = {
            "count": len(chunks),
            "wall_s_total": sum(walls),
            "wall_s_mean": sum(walls) / len(walls),
            "wall_s_p50": _percentile(walls, 0.5),
            "wall_s_max": max(walls),
            "retries": sum(e.get("retries", 0) for e in chunks),
        }
    out["interruptions"] = [
        {k: e.get(k) for k in ("event", "chunk", "start_chunk", "signal",
                               "attempt", "error") if k in e}
        for e in events
        if e.get("event") in ("retry", "resume", "preempted")
    ]
    cells = [e for e in events if e.get("event") == "bench_cell"]
    if cells:
        out["bench_cells"] = {e["cell"]: e["value"] for e in cells}
        # Compile-cost column (plain v2 bench_cell fields, no schema
        # bump): first-call wall time — compile + warmup — per cell.
        compile_cost = {
            e["cell"]: e["value"]["compile_wall_s"]
            for e in cells
            if isinstance(e.get("value"), dict)
            and e["value"].get("compile_wall_s") is not None
        }
        if compile_cost:
            out["compile_cost"] = compile_cost

    # AOT serve ladder (schema v3): which rung every served entrypoint
    # call landed on — bundle_exec/bundle_export are precompiled,
    # jit_cached/jit_cold mean the process is still paying compiles.
    aserves = [e for e in events if e.get("event") == "aot_serve"]
    if aserves:
        rungs_by_entry: dict[str, dict[str, int]] = {}
        for e in aserves:
            per = rungs_by_entry.setdefault(e.get("entry", "?"), {})
            r = e.get("rung", "?")
            per[r] = per.get(r, 0) + 1
        out["aot"] = {
            "serves": len(aserves),
            "rungs_by_entry": rungs_by_entry,
            "compiled_in_process": sum(
                1 for e in aserves
                if str(e.get("rung", "")).startswith("jit_")
            ),
            "wall_s_total": sum(
                e.get("wall_s", 0.0) for e in aserves
                if isinstance(e.get("wall_s"), (int, float))
            ),
        }

    # Serving tier (schema v4): request/batch lifecycle from serving/.
    # DEDUP RULE (the topology-table rule below, request-side): metrics
    # files APPEND across --resume / re-measured runs, so the same
    # request_id can carry several terminal events and the same
    # (batch_id, chunk) several boundaries — aggregate per identity with
    # the LAST event winning, or re-runs skew every percentile row.
    sevents = [e for e in events if e.get("event") == "serving_event"]
    if sevents:
        kinds: dict[str, int] = {}
        for e in sevents:
            k = e.get("kind", "?")
            kinds[k] = kinds.get(k, 0) + 1
        # Terminal outcome per request: last completed/rejected/
        # deadline_missed event wins (a resume legitimately re-resolves
        # a restored request; only its final resolution counts).
        terminal: dict[str, dict] = {}
        for e in sevents:
            if e.get("kind") in ("completed", "rejected",
                                 "deadline_missed"):
                terminal[e.get("request_id", "?")] = e
        completed = [e for e in terminal.values()
                     if e.get("kind") == "completed"]
        lat = [e["slo"]["latency_s"] for e in completed
               if isinstance(e.get("slo"), dict)
               and "latency_s" in e["slo"]]
        a2c = [e["slo"]["admit_to_complete_s"] for e in completed
               if isinstance(e.get("slo"), dict)
               and "admit_to_complete_s" in e["slo"]]
        rejections: dict[str, int] = {}
        for e in terminal.values():
            if e.get("kind") == "rejected":
                r = e.get("reason", "?")
                rejections[r] = rejections.get(r, 0) + 1
        misses: dict[str, int] = {}
        for e in terminal.values():
            if e.get("kind") == "deadline_missed":
                m = e.get("missed", "?")
                misses[m] = misses.get(m, 0) + 1
        # One boundary per (batch_id, chunk), last wins.
        bound_by_id: dict[tuple, dict] = {}
        for e in sevents:
            if e.get("kind") == "batch_boundary":
                bound_by_id[(e.get("batch_id"), e.get("chunk"))] = e
        bounds = list(bound_by_id.values())
        occ = [e["occupancy"] for e in bounds
               if isinstance(e.get("occupancy"), (int, float))]
        batches: dict = {}
        for e in sevents:
            if e.get("kind") == "batch_launch":
                batches[e.get("batch_id")] = {
                    "family": e.get("family"), "bucket": e.get("bucket"),
                    "rungs": {},
                }
        for e in bounds:
            b = batches.setdefault(
                e.get("batch_id"), {"family": e.get("family"),
                                    "bucket": None, "rungs": {}},
            )
            r = e.get("rung", "?")
            b["rungs"][r] = b["rungs"].get(r, 0) + 1
        # Result-cache effectiveness (schema v7, serving/cache.py):
        # hit rate over all submit-side outcomes — a cache_hit resolves
        # at submit INSTEAD of a "submitted" event, so the denominator is
        # their sum, not a subset.
        cache_hits = kinds.get("cache_hit", 0)
        cache_lookups = cache_hits + kinds.get("submitted", 0)
        out["serving"] = {
            "kinds": kinds,
            "completed": len(completed),
            "rejections": rejections,
            "deadline_misses": misses,
            "latency_s": _latency_stats(lat),
            "admit_to_complete_s": _latency_stats(a2c),
            "mean_occupancy": (sum(occ) / len(occ)) if occ else None,
            "cache_hits": cache_hits,
            "cache_hit_rate": (cache_hits / cache_lookups
                               if cache_lookups else None),
            "batches": batches,
        }

    # Serving fleet (schema v6): replica lifecycle + failover +
    # per-tenant admission from serving/fleet.py. Same append-mode dedup
    # discipline as the serving section: transitions dedup per
    # (replica, seq) and failovers per request_id, LAST event wins (a
    # restarted storm re-appends; only the final record counts).
    fevents = [e for e in events if e.get("event") == "fleet_event"]
    if fevents:
        kinds = {}
        for e in fevents:
            k = e.get("kind", "?")
            kinds[k] = kinds.get(k, 0) + 1
        trans_by_id: dict[tuple, dict] = {}
        hb: dict[str, int] = {}
        restarts: dict[str, int] = {}
        quarantined: list = []
        fail_by_req: dict[str, dict] = {}
        throttled: dict[str, int] = {}
        for e in fevents:
            k = e.get("kind")
            rep = str(e.get("replica", "?"))
            if k == "transition":
                trans_by_id[(rep, e.get("seq"))] = e
            elif k == "heartbeat":
                hb[rep] = hb.get(rep, 0) + 1
            elif k == "restart":
                restarts[rep] = max(restarts.get(rep, 0),
                                    e.get("attempt", 0))
            elif k == "quarantine":
                quarantined.append(rep)
            elif k == "failover":
                fail_by_req[e.get("request_id", "?")] = e
            elif k == "tenant_rejected":
                t = str(e.get("tenant", "?"))
                throttled[t] = throttled.get(t, 0) + 1
        transitions = sorted(
            trans_by_id.values(),
            key=lambda e: (e.get("seq") is None, e.get("seq")),
        )
        fail_lat = [e["latency_s"] for e in fail_by_req.values()
                    if isinstance(e.get("latency_s"), (int, float))]
        # Per-tenant admission ledger from the serving_event stream
        # (tenant is an additive field): admits per submitted event,
        # terminal outcomes deduped per request_id (last wins).
        tenant_term: dict[str, dict] = {}
        tenants: dict[str, dict] = {}
        seen_submit: set = set()
        for e in sevents:
            t = e.get("tenant")
            if t is None:
                continue
            row = tenants.setdefault(str(t), {
                "submitted": 0, "completed": 0, "rejected": 0,
                "throttled": 0, "latency": [],
            })
            if e.get("kind") == "submitted":
                # Dedup per request_id: the front, the owning replica,
                # a failover re-dispatch and a resume each re-emit the
                # submit — one logical admission.
                rid = e.get("request_id")
                if rid not in seen_submit:
                    seen_submit.add(rid)
                    row["submitted"] += 1
            elif e.get("kind") in ("completed", "rejected",
                                   "deadline_missed"):
                tenant_term[e.get("request_id", "?")] = e
        for e in tenant_term.values():
            row = tenants.setdefault(str(e.get("tenant")), {
                "submitted": 0, "completed": 0, "rejected": 0,
                "throttled": 0, "latency": [],
            })
            if e.get("kind") == "completed":
                row["completed"] += 1
                if (isinstance(e.get("slo"), dict)
                        and "latency_s" in e["slo"]):
                    row["latency"].append(e["slo"]["latency_s"])
            elif e.get("kind") == "rejected":
                row["rejected"] += 1
        for t, n in throttled.items():
            tenants.setdefault(t, {
                "submitted": 0, "completed": 0, "rejected": 0,
                "throttled": 0, "latency": [],
            })["throttled"] = n
        out["fleet"] = {
            "kinds": kinds,
            "transitions": [
                {k: e.get(k) for k in ("seq", "replica", "from_state",
                                       "to_state", "reason")}
                for e in transitions
            ],
            "heartbeats": hb,
            "restarts": restarts,
            "quarantined": sorted(set(quarantined)),
            "failovers": len(fail_by_req),
            "failover_latency_s": _latency_stats(fail_lat),
            "duplicates_dropped": kinds.get("duplicate_result", 0),
            "tenants": {
                t: {
                    "submitted": r["submitted"],
                    "completed": r["completed"],
                    "rejected": r["rejected"],
                    "throttled": r["throttled"],
                    "latency_s": _latency_stats(r["latency"]),
                }
                for t, r in sorted(tenants.items())
            },
        }

    # Closed-loop sessions (schema v8): lease lifecycle + per-step SLO
    # from serving/sessions.py. Append-mode dedup discipline: lifecycle
    # state dedups per session_id (LAST opened/evicted/session_closed
    # wins — a resumed run re-appends "opened" for restored sessions)
    # and step terminals dedup per (session_id, step_seq) (last
    # step_done/step_degraded wins), while the raw kind counts stay
    # honest about every event observed.
    xevents = [e for e in events if e.get("event") == "session_event"]
    if xevents:
        kinds = {}
        for e in xevents:
            k = e.get("kind", "?")
            kinds[k] = kinds.get(k, 0) + 1
        life: dict[str, str] = {}
        steps: dict[tuple, dict] = {}
        gaps: list[float] = []
        for e in xevents:
            k = e.get("kind")
            sid = str(e.get("session_id", "?"))
            if k == "opened":
                life[sid] = "live"
            elif k == "evicted":
                life[sid] = "evicted"
            elif k == "session_closed":
                life[sid] = "closed"
            elif k in ("step_done", "step_degraded"):
                steps[(sid, e.get("step_seq"))] = e
            if k in ("renewed", "evicted") and isinstance(
                    e.get("gap_s"), (int, float)):
                gaps.append(e["gap_s"])
        lat: list[float] = []
        degraded = 0
        served = 0
        rejected_steps = 0
        for e in steps.values():
            rung = e.get("rung")
            if e.get("kind") == "step_degraded":
                degraded += 1
            elif rung == "rejected":
                rejected_steps += 1
            else:
                served += 1
            slo = e.get("slo")
            if isinstance(slo, dict) and isinstance(
                    slo.get("latency_s"), (int, float)):
                lat.append(slo["latency_s"])
        # Heartbeat-gap histogram: fixed edges in seconds. The gap is
        # renew-to-renew (or renew-to-eviction) silence — the tail
        # buckets are where lease tuning (TAT_SESSION_LEASE_S) lives.
        edges = (0.1, 0.5, 1.0, 5.0, 30.0)
        hist = {f"<{edges[0]}": 0}
        for lo, hi in zip(edges, edges[1:]):
            hist[f"{lo}-{hi}"] = 0
        hist[f">={edges[-1]}"] = 0
        for g in gaps:
            if g < edges[0]:
                hist[f"<{edges[0]}"] += 1
            elif g >= edges[-1]:
                hist[f">={edges[-1]}"] += 1
            else:
                for lo, hi in zip(edges, edges[1:]):
                    if lo <= g < hi:
                        hist[f"{lo}-{hi}"] += 1
                        break
        n_steps = len(steps)
        # Autoscale hint trail rides the fleet_event stream (additive
        # v8 kind): the LAST confirmed hint wins; the transition count
        # is a flap meter (hysteresis should keep it tiny).
        auto = [e for e in fevents if e.get("kind") == "autoscale"]
        out["sessions"] = {
            "kinds": kinds,
            "live": sum(1 for s in life.values() if s == "live"),
            "evicted": sum(1 for s in life.values() if s == "evicted"),
            "closed": sum(1 for s in life.values() if s == "closed"),
            "fence_rejections": kinds.get("fenced", 0),
            "stale_rejections": kinds.get("stale_step", 0),
            "steps": n_steps,
            "step_latency_s": _latency_stats(lat),
            "degraded_steps": degraded,
            "served_steps": served,
            "rejected_steps": rejected_steps,
            "degraded_rate": (degraded / n_steps) if n_steps else None,
            "heartbeat_gap_hist": hist,
            "rehomed": kinds.get("rehomed", 0),
            "autoscale": {
                "hint": auto[-1].get("hint") if auto else None,
                "transitions": len(auto),
            },
        }

    # Critical path (schema v5, obs.trace): decompose each traced
    # request's submit→complete interval into queue-wait / batch-wait /
    # device / harvest / retry segments — "why did p99 regress" as a
    # table instead of an archaeology session. Re-measured requests in
    # an append-mode file dedup per request_id (last request span wins,
    # inside critical_path).
    trows = trace_lib.trace_rows(events)
    if trows:
        cp = trace_lib.critical_path(trace_lib.stitch(trows))
        if cp["requests"]:
            out["critical_path"] = cp

    # Topology (pods tier): per-cell process/device counts + mesh shapes
    # (plain additive bench_cell value fields, _annotate_topology),
    # classified topology_mismatch events, and the pods cells' rungs —
    # the MULTICHIP_r0x trail as tables instead of raw JSON tails.
    # Dedup by cell, LAST event wins (the metrics file appends across
    # --resume / cell-filtered re-runs — same rule as bench_cells above;
    # counting per event would double-count re-measured cells).
    topo_by_cell: dict[str, dict] = {}
    for e in cells:
        v = e.get("value")
        if isinstance(v, dict) and ("n_devices" in v or "mesh" in v):
            topo_by_cell[e["cell"]] = {
                "cell": e["cell"],
                "n_processes": v.get("n_processes"),
                "n_devices": v.get("n_devices"),
                "mesh": v.get("mesh"),
                "rung": v.get("rung"),
                "skipped": v.get("skipped"),
            }
    topo_rows = list(topo_by_cell.values())
    mismatches = [
        {k: e.get(k) for k in ("label", "rung", "detail") if k in e}
        for e in events
        if e.get("event") == "backend_event"
        and e.get("kind") == "topology_mismatch"
    ]
    if topo_rows or mismatches:
        shapes: dict[str, int] = {}
        for r in topo_rows:
            key = f"{r['n_processes']}proc x {r['n_devices']}dev"
            shapes[key] = shapes.get(key, 0) + 1
        out["topology"] = {
            "shapes": shapes,
            "mismatch_events": mismatches,
            "pods_cells": [r for r in topo_rows
                           if r["cell"].startswith("pods")],
        }

    # Backend guard (schema v2): error/circuit events from
    # resilience.backend.BackendGuard, plus the rung each cell/chunk
    # ACTUALLY ran at (bench cells carry it in their value dict, chunk
    # events as a top-level field).
    bevents = [e for e in events if e.get("event") == "backend_event"]
    # (unit, impl, solve, rung) rows: impl is the consensus-exchange impl
    # the ring A/B cells (bench.py _sharded_ab_cell) carry in their value
    # dict — "impl(resolved)" when a pallas_ring cell downgraded off-TPU —
    # and solve is the inner-solve impl the fused A/B cells
    # (bench.py _fused_ab_cell) carry: the fused mode, rendered
    # "kernel(scan)" when the whole-solve kernel downgraded off-TPU, with
    # a "/bf16" (or "/bf16(f32)" after a parity-bar refusal) storage
    # suffix. Plain v4 bench_cell value fields; no schema change.
    rungs: list[tuple] = []
    for e in cells:
        v = e.get("value")
        if isinstance(v, dict) and "rung" in v:
            impl = v.get("impl", "")
            resolved = v.get("impl_resolved", impl)
            if resolved and resolved != impl:
                impl = f"{impl}({resolved})"
            solve = v.get("fused", "")
            fr = v.get("fused_resolved", solve)
            if fr and fr != solve:
                solve = f"{solve}({fr})"
            prec = v.get("precision")
            if prec and prec != "f32":
                pr = v.get("precision_resolved", prec)
                solve += f"/{prec}" if pr == prec else f"/{prec}({pr})"
            # Solver-effort columns (the effort A/B cells, bench.py
            # _effort_ab_cell; plain v4 value fields, no schema bump):
            # the knob ("fixed(adaptive)" when request != resolved) and
            # the measured consensus-iteration mean/p99 any
            # rollout-shaped cell may carry.
            effort = v.get("effort", "")
            er = v.get("effort_resolved", effort)
            if er and er != effort:
                effort = f"{effort}({er})"
            im, ip = v.get("iters_mean"), v.get("iters_p99")
            iters = "" if im is None else (
                f"{im:.1f}" + ("" if ip is None else f"/{ip:g}")
            )
            # Environment-query column (the env_{dense,bucketed}_T* A/B
            # cells, bench.py _env_query_cell; plain value fields):
            # impl("resolved" when they differ, the exchange-impl
            # convention) plus the bucketed arm's slab width — the grid
            # occupancy telemetry's headline number.
            envq = v.get("env_query", "")
            eqr = v.get("env_query_resolved", envq)
            if eqr and eqr != envq:
                envq = f"{envq}({eqr})"
            g = v.get("grid")
            if envq and isinstance(g, dict) and "k" in g:
                envq += f" K={g['k']}"
            rungs.append((e["cell"], impl, solve, v["rung"], effort,
                          iters, envq))
    for e in chunks:
        if "rung" in e:
            rungs.append((f"chunk {e['chunk']}", "", "", e["rung"], "",
                          "", ""))
    # SLO alert trail (schema v9, obs.live.SLOEngine): fire/resolve
    # transitions in journal order. An alert with no later resolve for
    # its (slo, tenant) key is UNRESOLVED — the examples' nonzero-exit
    # criterion and the headline render line.
    aevents = [e for e in events if e.get("event") == "alert"]
    if aevents:
        akinds: dict[str, int] = {}
        open_alerts: dict[tuple, dict] = {}
        for e in aevents:
            k = e.get("kind", "?")
            akinds[k] = akinds.get(k, 0) + 1
            key = (e.get("slo"), e.get("tenant"))
            if k == "fire":
                open_alerts[key] = e
            elif k == "resolve":
                open_alerts.pop(key, None)
        out["alerts"] = {
            "events": len(aevents),
            "fired": akinds.get("fire", 0),
            "resolved": akinds.get("resolve", 0),
            "unresolved": sorted(
                f"{s}/{t}" for s, t in open_alerts
            ),
            "trail": [
                {k: e.get(k)
                 for k in ("kind", "slo", "tenant", "severity",
                           "burn_rate", "window_s", "ts", "fired_ts")
                 if k in e}
                for e in aevents
            ],
        }

    if bevents or rungs:
        kinds: dict[str, int] = {}
        for e in bevents:
            k = e.get("kind", "?")
            kinds[k] = kinds.get(k, 0) + 1
        out["backend"] = {
            "events": len(bevents),
            "kinds": kinds,
            "timeouts": kinds.get("wedge_timeout", 0),
            "transitions": [
                {k: e.get(k) for k in ("kind", "label", "reason", "detail")
                 if k in e}
                for e in bevents if e.get("kind", "").startswith("circuit_")
            ],
            "errors": [
                {k: e.get(k) for k in ("kind", "label", "rung", "detail")
                 if k in e}
                for e in bevents
                if not e.get("kind", "").startswith("circuit_")
            ],
            "rungs": rungs,
        }
    return out


def render(summary: dict) -> None:
    ev = summary["events"]
    print("# run health")
    print("events: " + ", ".join(
        f"{k}={v}" for k, v in sorted(ev.items())
    ))

    tel = summary.get("telemetry")
    logs = summary.get("logs")
    rung_src = None
    if tel:
        rung_src = ("telemetry (cumulative, on-device)", tel["rung_hist"],
                    tel["steps"])
    elif logs:
        rung_src = ("log digests (exact)", logs["rung_hist"], logs["steps"])
    if rung_src:
        label, hist, steps = rung_src
        print(f"\n## fallback-rung distribution — {label}")
        print("| rung | steps | % |")
        print("|---|---|---|")
        for name, count in zip(RUNG_LABELS, hist):
            pct = 100.0 * count / steps if steps else 0.0
            print(f"| {name} | {count} | {pct:.1f} |")

    if tel:
        r = tel["residual"]
        # Percentile columns come from the event's own keys (the state
        # carries its quantile labels), so non-default configs render
        # their actual percentiles instead of empty p50/p90/p99 columns.
        pkeys = sorted(
            (k for k in r if k.startswith("p") and k != "pct"),
            key=lambda k: float(k[1:]),
        )
        cols = ["count", "min", *pkeys, "max", "mean"]
        print("\n## consensus residual (P² streaming percentiles)")
        print("| " + " | ".join(cols) + " |")
        print("|" + "---|" * len(cols))
        print("| " + " | ".join(
            [str(r["count"])] + [_fmt(r.get(k)) for k in cols[1:]]
        ) + " |")
        print("\n## safety margins")
        if "lanes" in tel:
            print(f"- fleet lanes (batched run, worst-lane percentiles): "
                  f"{tel['lanes']}")
        print(f"- min env/CBF margin: {_fmt(tel['min_env_dist'])} m")
        print(f"- worst-step ok_frac: {_fmt(tel['ok_frac_min'])}")
        print(f"- collision steps: {tel['collision_steps']}")
        print(f"- quarantined steps: {tel['quarantine_steps']}")
        if "agent_fail_steps" in tel:
            worst = max(range(len(tel["agent_fail_steps"])),
                        key=lambda i: tel["agent_fail_steps"][i])
            print(f"- per-agent solve failures: {tel['agent_fail_steps']} "
                  f"(worst: agent {worst})")
        eff = tel.get("effort")
        if eff and sum(eff.get("consensus_hist", [])):
            # Solver-effort histograms (adaptive-effort observability;
            # obs.telemetry ITER_BUCKETS log2 grid).
            print("\n## solver effort (iteration histograms)")
            print(f"- consensus iters/step: mean {_fmt(eff['iters_mean'])}"
                  f", p99 <= {_fmt(eff['iters_p99'])}")
            if "inner_iters_sum" in eff:
                print(f"- inner iters total: {eff['inner_iters_sum']} "
                      f"(per solve: mean "
                      f"{_fmt(eff.get('inner_per_solve_mean'))}, "
                      f"p99 <= "
                      f"{_fmt(eff.get('inner_per_solve_p99'))})")
            edges = [str(b) for b in eff["buckets"]] + [
                f">{eff['buckets'][-1]}"
            ]
            rows = [("consensus", eff["consensus_hist"])]
            if "inner_hist" in eff:
                rows.append(("inner/solve", eff["inner_hist"]))
            print("| histogram | " + " | ".join(
                f"<={e}" if not e.startswith(">") else e for e in edges
            ) + " |")
            print("|" + "---|" * (len(edges) + 1))
            for label, hist in rows:
                print(f"| {label} | " + " | ".join(
                    str(c) for c in hist
                ) + " |")
    elif logs:
        print("\n## safety margins (from log digests)")
        print(f"- min env/CBF margin: {_fmt(logs['min_env_dist'])} m")
        print(f"- collision steps: {logs['collision_steps']}")
        print(f"- quarantined lanes at end: {logs['quarantined_final']}")

    ch = summary.get("chunks")
    if ch:
        print("\n## chunk wall-times")
        print(f"- chunks: {ch['count']}, total {ch['wall_s_total']:.2f} s")
        print(f"- per-chunk mean/p50/max: {ch['wall_s_mean']:.3f} / "
              f"{ch['wall_s_p50']:.3f} / {ch['wall_s_max']:.3f} s")
        print(f"- host-level retries: {ch['retries']}")

    if summary.get("interruptions"):
        print("\n## resume / retry / preemption events")
        for e in summary["interruptions"]:
            print(f"- {json.dumps(e)}")

    if summary.get("bench_cells"):
        print("\n## bench cells")
        print("| cell | value |")
        print("|---|---|")
        for k, v in summary["bench_cells"].items():
            print(f"| {k} | {json.dumps(v)} |")

    if summary.get("compile_cost"):
        print("\n## compile cost (first-call wall time per cell)")
        print("| cell | compile_wall_s |")
        print("|---|---|")
        for k, v in summary["compile_cost"].items():
            print(f"| {k} | {v:.2f} |")
        print(f"| **total** | "
              f"{sum(summary['compile_cost'].values()):.2f} |")

    ao = summary.get("aot")
    if ao:
        print("\n## AOT serve ladder (aot.loader)")
        print(f"- serves: {ao['serves']} "
              f"(in-process compiles: {ao['compiled_in_process']}, "
              f"total wall {ao['wall_s_total']:.2f} s)")
        print("| entry | rung | serves |")
        print("|---|---|---|")
        for entry, per in ao["rungs_by_entry"].items():
            for rung, n in sorted(per.items()):
                print(f"| {entry} | {rung} | {n} |")

    sv = summary.get("serving")
    if sv:
        print("\n## serving SLO (serving/ tier)")
        print("events: " + ", ".join(
            f"{k}={v}" for k, v in sorted(sv["kinds"].items())
        ))
        for label, key in (("submit→complete", "latency_s"),
                           ("admit→complete", "admit_to_complete_s")):
            st = sv.get(key)
            if st:
                print(f"- {label} latency: p50 {_fmt(st['p50'])} s, "
                      f"p90 {_fmt(st['p90'])} s, p99 {_fmt(st['p99'])} s "
                      f"(mean {_fmt(st['mean'])}, n={st['count']})")
        if sv["rejections"]:
            print("- rejections: " + ", ".join(
                f"{k}={v}" for k, v in sorted(sv["rejections"].items())
            ))
        if sv["deadline_misses"]:
            print("- deadline misses: " + ", ".join(
                f"{k}={v}" for k, v in sorted(
                    sv["deadline_misses"].items())
            ))
        if sv["mean_occupancy"] is not None:
            print(f"- mean batch occupancy: "
                  f"{sv['mean_occupancy']:.3f}")
        if sv.get("cache_hits"):
            rate = sv.get("cache_hit_rate")
            print(f"- result-cache hits: {sv['cache_hits']}"
                  + (f" (hit rate {rate:.3f})" if rate is not None
                     else ""))
        if sv["batches"]:
            print("\n| batch | family | bucket | rungs |")
            print("|---|---|---|---|")
            for bid, b in sorted(sv["batches"].items(),
                                 key=lambda kv: str(kv[0])):
                rungs = ", ".join(
                    f"{r}×{n}" for r, n in sorted(b["rungs"].items())
                ) or "—"
                print(f"| {bid} | {b['family']} | "
                      f"{b['bucket'] if b['bucket'] is not None else '—'} "
                      f"| {rungs} |")

    fl = summary.get("fleet")
    if fl:
        print("\n## serving fleet (serving/fleet.py)")
        print("events: " + ", ".join(
            f"{k}={v}" for k, v in sorted(fl["kinds"].items())
        ))
        if fl["transitions"]:
            print("\n| seq | replica | transition | reason |")
            print("|---|---|---|---|")
            for t in fl["transitions"]:
                print(f"| {t.get('seq', '—')} | r{t['replica']} | "
                      f"{t['from_state']} → {t['to_state']} | "
                      f"{(t.get('reason') or '')[:60]} |")
        hb = ", ".join(f"r{r}={n}" for r, n in sorted(fl["heartbeats"].items()))
        print(f"- heartbeats: {hb or 'none'}")
        if fl["restarts"]:
            print("- restarts: " + ", ".join(
                f"r{r}×{n}" for r, n in sorted(fl["restarts"].items())
            ))
        if fl["quarantined"]:
            print(f"- quarantined replicas: "
                  f"{', '.join('r' + r for r in fl['quarantined'])}")
        st = fl.get("failover_latency_s")
        print(f"- failovers: {fl['failovers']} "
              + (f"(re-dispatch latency p50 {_fmt(st['p50'])} s, "
                 f"p99 {_fmt(st['p99'])} s, max {_fmt(st['max'])} s)"
                 if st else "")
              + (f", duplicates dropped: {fl['duplicates_dropped']}"
                 if fl["duplicates_dropped"] else ""))
        if fl["tenants"]:
            print("\n| tenant | submitted | completed | rejected | "
                  "throttled | p50 s | p99 s |")
            print("|---|---|---|---|---|---|---|")
            for t, r in fl["tenants"].items():
                lat = r["latency_s"]
                print(f"| {t} | {r['submitted']} | {r['completed']} | "
                      f"{r['rejected']} | {r['throttled']} | "
                      f"{_fmt(lat['p50']) if lat else '—'} | "
                      f"{_fmt(lat['p99']) if lat else '—'} |")

    sx = summary.get("sessions")
    if sx:
        print("\n## closed-loop sessions (serving/sessions.py)")
        print("events: " + ", ".join(
            f"{k}={v}" for k, v in sorted(sx["kinds"].items())
        ))
        print(f"- sessions: live={sx['live']}, evicted={sx['evicted']}, "
              f"closed={sx['closed']}"
              + (f", rehomed={sx['rehomed']}" if sx["rehomed"] else ""))
        print(f"- rejections: fenced={sx['fence_rejections']}, "
              f"stale_step={sx['stale_rejections']}")
        st = sx.get("step_latency_s")
        if st:
            print(f"- per-step latency: p50 {_fmt(st['p50'])} s, "
                  f"p99 {_fmt(st['p99'])} s (mean {_fmt(st['mean'])}, "
                  f"n={st['count']})")
        if sx["steps"]:
            rate = sx["degraded_rate"]
            print(f"- steps: {sx['steps']} "
                  f"(served {sx['served_steps']}, "
                  f"degraded {sx['degraded_steps']}, "
                  f"rejected {sx['rejected_steps']}"
                  + (f"; degraded-rung rate {rate:.3f}"
                     if rate is not None else "")
                  + ")")
        hist = sx["heartbeat_gap_hist"]
        if any(hist.values()):
            print("- heartbeat gaps (s): " + ", ".join(
                f"{b}={n}" for b, n in hist.items() if n
            ))
        au = sx["autoscale"]
        if au["hint"] is not None or au["transitions"]:
            print(f"- autoscale: hint={au['hint'] or '—'} "
                  f"({au['transitions']} confirmed transitions)")

    al = summary.get("alerts")
    if al:
        print("\n## slo alerts (obs.live burn-rate engine)")
        print(f"- fired: {al['fired']}, resolved: {al['resolved']}, "
              f"unresolved: {len(al['unresolved'])}"
              + (f" ({', '.join(al['unresolved'])})"
                 if al["unresolved"] else ""))
        for e in al["trail"]:
            if e["kind"] == "fire":
                print(f"  - FIRE {e.get('slo')}/{e.get('tenant')} "
                      f"severity={e.get('severity')} "
                      f"burn={_fmt(e.get('burn_rate'))} "
                      f"window={e.get('window_s')}s "
                      f"ts={_fmt(e.get('ts'))}")
            else:
                print(f"  - resolve {e.get('slo')}/{e.get('tenant')} "
                      f"ts={_fmt(e.get('ts'))} "
                      f"(fired ts={_fmt(e.get('fired_ts'))})")

    cp = summary.get("critical_path")
    if cp:
        print("\n## critical path (distributed tracing, obs.trace)")
        print(f"- traced requests: {len(cp['requests'])} "
              f"({cp['completed']} completed)")
        if cp["per_segment"]:
            print("\n| segment | p50 s | p99 s | mean s | total s |")
            print("|---|---|---|---|---|")
            for seg in trace_lib.SEGMENTS:
                st = cp["per_segment"].get(seg)
                if st is None:
                    continue
                print(f"| {seg} | {_fmt(st['p50'])} | {_fmt(st['p99'])} "
                      f"| {_fmt(st['mean'])} | {_fmt(st['total'])} |")
        w = cp.get("worst")
        if w:
            segs = ", ".join(
                f"{k}={_fmt(v)}" for k, v in w["segments"].items() if v
            )
            print(f"- worst request: {w['request_id']} "
                  f"(total {_fmt(w['total_s'])} s: {segs})")

    tp = summary.get("topology")
    if tp:
        print("\n## topology (pods tier / parallel.pods)")
        print("- cell topologies: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(tp["shapes"].items())
        ))
        if tp["mismatch_events"]:
            print("- topology_mismatch events:")
            for m in tp["mismatch_events"]:
                print(f"  - {m.get('label')}: "
                      f"{(m.get('detail') or '')[:140]}")
        if tp["pods_cells"]:
            print("\n| pods cell | mesh | procs | devices | rung |")
            print("|---|---|---|---|---|")
            for r in tp["pods_cells"]:
                mesh = r["mesh"]
                mesh_s = ("x".join(str(v) for v in mesh.values())
                          if isinstance(mesh, dict) else "—")
                rung = r.get("rung") or (
                    f"skipped: {r['skipped']}" if r.get("skipped") else "—"
                )
                print(f"| {r['cell']} | {mesh_s} | "
                      f"{r['n_processes'] if r['n_processes'] is not None else '—'} | "
                      f"{r['n_devices'] if r['n_devices'] is not None else '—'} | "
                      f"{rung} |")

    be = summary.get("backend")
    if be:
        print("\n## backend health (resilience.backend guard)")
        kinds = ", ".join(f"{k}={v}" for k, v in sorted(be["kinds"].items())) \
            or "none"
        print(f"- guard events: {be['events']} ({kinds})")
        print(f"- watchdog timeouts: {be['timeouts']}")
        if be["transitions"]:
            print("- circuit transitions:")
            for t in be["transitions"]:
                print(f"  - {t.get('kind')} at {t.get('label')}: "
                      f"{t.get('reason', t.get('detail', ''))}")
        if be["errors"]:
            print("- classified backend errors:")
            for e in be["errors"]:
                print(f"  - [{e.get('kind')}] {e.get('label')} "
                      f"(ran at {e.get('rung', '?')}): "
                      f"{(e.get('detail') or '')[:120]}")
        if be["rungs"]:
            print("\n| unit | exchange impl | solve impl | effort | "
                  "iters mean/p99 | env query | rung |")
            print("|---|---|---|---|---|---|---|")
            for unit, impl, solve, rung, *rest in be["rungs"]:
                effort = rest[0] if rest else ""
                iters = rest[1] if len(rest) > 1 else ""
                envq = rest[2] if len(rest) > 2 else ""
                print(f"| {unit} | {impl or '—'} | {solve or '—'} | "
                      f"{effort or '—'} | {iters or '—'} | "
                      f"{envq or '—'} | {rung} |")


def _latency_stats(xs: list[float]) -> dict | None:
    if not xs:
        return None
    return {
        "count": len(xs),
        "mean": sum(xs) / len(xs),
        "p50": _percentile(xs, 0.5),
        "p90": _percentile(xs, 0.9),
        "p99": _percentile(xs, 0.99),
        "max": max(xs),
    }


def _fmt(v) -> str:
    if v is None:
        return "—"
    return f"{v:.4g}"


def follow(args) -> None:
    """Live vitals: tail the paths and redraw one rolling-window
    per-tenant table each refresh (the fleet_console's single-window
    little sibling; --rounds bounds the loop for tests)."""
    tailer = live_mod.FleetTailer(args.paths)
    windows = live_mod.RollingWindows(
        horizon_s=max(3600, int(args.window))
    )
    refresh = live_mod.resolve_refresh_s(args.refresh)
    rounds = 0
    while True:
        for replica, event in tailer.poll():
            windows.ingest(replica, event)
        rates = windows.rates(int(args.window))
        if args.json:
            print(json.dumps({"now": windows.latest_ts,
                              "window_s": int(args.window),
                              "tenants": rates}))
        else:
            print(f"-- trailing {int(args.window)}s @ "
                  f"ts={_fmt(windows.latest_ts)} --")
            if not rates:
                print("  (no traffic)")
            for tenant, row in sorted(rates.items()):
                lat = row["latency"]
                print(f"  {tenant}: submitted={row.get('submitted', 0)} "
                      f"completed={row.get('completed', 0)} "
                      f"rejected={row.get('rejected', 0)} "
                      f"missed={row.get('missed', 0)} "
                      f"steps={row.get('steps', 0)} "
                      f"p99={_fmt(lat['p99'])}s "
                      f"miss_rate={_fmt(row['miss_rate'])} "
                      f"rejection_rate={_fmt(row['rejection_rate'])}")
        rounds += 1
        if args.rounds is not None and rounds >= args.rounds:
            return
        time.sleep(refresh)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="+", metavar="METRICS_JSONL")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary instead of "
                         "tables")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check only (ci gate); exit 1 on any "
                         "violation")
    ap.add_argument("--follow", action="store_true",
                    help="live mode: tail the paths (files or dirs of "
                         "*.metrics.jsonl) and redraw rolling rates")
    ap.add_argument("--window", type=int, default=60,
                    help="trailing window in seconds for --follow "
                         "(default 60)")
    ap.add_argument("--refresh", type=float, default=None,
                    help="--follow refresh period in seconds "
                         "(TAT_CONSOLE_REFRESH_S overrides; default 1)")
    ap.add_argument("--rounds", type=int, default=None,
                    help="stop --follow after N refreshes (tests)")
    args = ap.parse_args()

    if args.follow:
        follow(args)
        return

    failed = False
    for path in args.paths:
        errs = export_mod.validate_file(path)
        if errs:
            failed = True
            print(f"{path}: {len(errs)} schema violation(s)",
                  file=sys.stderr)
            for e in errs[:20]:
                print(f"  {e}", file=sys.stderr)
        elif args.validate:
            print(f"{path}: OK")
    if args.validate or failed:
        raise SystemExit(1 if failed else 0)

    for path in args.paths:
        if len(args.paths) > 1:
            print(f"\n===== {path} =====")
        summary = summarize(export_mod.read_events(path))
        if args.json:
            print(json.dumps(summary, indent=1))
        else:
            render(summary)


if __name__ == "__main__":
    main()
