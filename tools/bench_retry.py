"""Retry/timeout/backoff harness for chip benchmark runs.

Four straight rounds lost their benches to a wedged TPU with nothing but a
bare ``value: null`` (or a hung process) as the record. This wrapper makes
the failure mode a MACHINE-READABLE artifact:

    python tools/bench_retry.py [--attempts N] [--timeout S] [--backoff S]
        [--out BENCH_ATTEMPT.json] [-- CMD ...]

Default CMD is ``python bench.py``. Each attempt is preceded by a chip
probe (tools/probe_chip.probe, a watchdogged subprocess touch of the
backend); the probe outcome classifies failures:

- ``wedged``: the probe (or the bench itself) TIMED OUT — a chip that
  accepts the connection but never answers;
- ``absent``: the probe failed FAST (plugin missing, no device, silent CPU
  fallback) — there is no chip to wait for, so remaining attempts are
  skipped;
- ``failed``: the chip probed alive but the bench command itself exited
  nonzero (a code problem, not an infra one);
- ``ok``: bench completed; its final JSON line is forwarded as ``result``.

The emitted JSON records every attempt (probe detail, rc, duration, last
stderr lines), the total probe count, the last error, and the
classification — exactly what a driver needs to file "the chip was wedged
for 90 minutes" instead of a silent absence of numbers. Exit status: 0 iff
classification is ``ok``.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from probe_chip import _backend_mod, probe  # noqa: E402

# resilience/backend.py loaded by FILE PATH (no package/jax import): the
# group-kill subprocess runner and the circuit breaker's backoff policy —
# ONE retry cadence for the whole stack.
_backend = _backend_mod()


def _utc() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


SWEEP_JOURNAL = "BENCH_SWEEP_JOURNAL.jsonl"

# Persistent XLA compilation cache for the bench children — the same knob
# the test conftest, bench.py, and the AOT serve driver share
# (utils/platform.py; "" disables). Every retry attempt re-runs the SAME
# programs: without a shared cache each attempt recompiled the full
# matrix from scratch inside its own timeout. Set inline (not imported)
# because this tool must not import the package — importing jax is the
# hazard it exists to contain.
XLA_CACHE_ENV = "TAT_XLA_CACHE_DIR"


def _child_env() -> dict:
    env = dict(os.environ)
    env.setdefault(XLA_CACHE_ENV, os.path.join(REPO, ".cache", "xla"))
    return env


def _journal_cells(cwd: str) -> int | None:
    """Completed-cell count from a crashed sweep's journal, ``None`` when
    no journal exists (nothing to resume). Tolerates a torn final line —
    the same contract as ``resilience.recovery.RunJournal.read``."""
    path = os.path.join(cwd, SWEEP_JOURNAL)
    if not os.path.exists(path):
        return None
    cells = set()
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("event") == "cell":
                cells.add(e.get("cell"))
    return len(cells)


def run_with_retries(
    cmd: list[str],
    attempts: int = 3,
    timeout_s: int = 900,
    backoff_s: float = 30.0,
    probe_timeout_s: int = 60,
    probe_fn=probe,
    cwd: str = REPO,
    backoff_jitter: float = 0.1,
) -> dict:
    """Run ``cmd`` with per-attempt chip probes, timeouts, and exponential
    backoff. Returns the structured record described in the module
    docstring (pure data — the CLI wrapper handles printing/exit).

    Sweep resume: when ``cmd`` is a ``--sweep`` run and a failed/wedged
    attempt left a sweep journal behind (``BENCH_SWEEP_JOURNAL.jsonl``),
    subsequent attempts get ``--resume`` appended so the sweep continues
    from the journaled cells instead of restarting from zero — the record
    carries ``resumed_from_chunk`` (restored-cell count at the time the
    resume was queued) and bench's own final JSON line reports the same
    field."""
    record = {
        "cmd": cmd,
        "started": _utc(),
        "attempts": [],
        "probe_count": 0,
        "classification": None,
        "last_error": None,
        "result": None,
    }
    # Same backoff+jitter policy as the circuit breaker
    # (resilience.backend.BackoffPolicy): exponential from ``backoff_s``,
    # jittered so a fleet of retriers sharing one wedged chip
    # decorrelates instead of thundering back in lockstep.
    policy = _backend.BackoffPolicy(
        initial_s=backoff_s, factor=2.0, max_s=max(backoff_s * 16, 600.0),
        jitter=backoff_jitter,
    )
    use_resume = False
    is_sweep = "--sweep" in cmd

    def _queue_resume():
        """After a failed sweep attempt: resume from the journal next time."""
        nonlocal use_resume
        if not is_sweep or "--resume" in cmd:
            return
        n = _journal_cells(cwd)
        if n:
            use_resume = True
            record["resumed_from_chunk"] = n

    for k in range(attempts):
        att = {"attempt": k + 1, "ts": _utc()}
        if use_resume:
            att["resumed"] = True
        ok, detail = probe_fn(timeout_s=probe_timeout_s)
        record["probe_count"] += 1
        att["probe_ok"] = ok
        att["probe_detail"] = detail
        if not ok:
            # Structured prefix from probe_chip.probe's TimeoutExpired
            # branch — NOT a substring match, which would misread a fast
            # rc!=0 failure whose stderr merely mentions a timeout (e.g.
            # "DEADLINE_EXCEEDED: rpc timeout") as a wedged chip.
            timed_out = detail.startswith("timeout after")
            att["error"] = f"chip probe failed: {detail}"
            record["attempts"].append(att)
            record["last_error"] = att["error"]
            if not timed_out:
                # No chip to wait for — retrying cannot help.
                record["classification"] = "absent"
                return _finalize(record)
            record["classification"] = "wedged"
        else:
            t0 = time.monotonic()
            cmd_k = cmd + ["--resume"] if use_resume else cmd
            try:
                # Own-session child + group SIGKILL on timeout: a wedged
                # bench's own subprocesses (probe children, runtime
                # helpers holding the chip) must not survive as orphans
                # wedging every later attempt (resilience.backend
                # run_group).
                proc = _backend.run_group(cmd_k, timeout_s, cwd=cwd,
                                          env=_child_env())
                att["duration_s"] = round(time.monotonic() - t0, 1)
                att["rc"] = proc.returncode
                if proc.returncode == 0:
                    att["ok"] = True
                    record["attempts"].append(att)
                    record["classification"] = "ok"
                    # Forward the bench's final JSON line when there is one.
                    for line in reversed(proc.stdout.strip().splitlines()):
                        try:
                            record["result"] = json.loads(line)
                            break
                        except ValueError:
                            continue
                    return record
                tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
                att["error"] = f"bench rc={proc.returncode}: " + " | ".join(tail)
                record["classification"] = "failed"
            except subprocess.TimeoutExpired:
                att["duration_s"] = round(time.monotonic() - t0, 1)
                att["error"] = (
                    f"bench timed out after {timeout_s}s (probe was alive — "
                    "chip wedged mid-run)"
                )
                record["classification"] = "wedged"
            record["attempts"].append(att)
            record["last_error"] = att["error"]
            _queue_resume()
        if k + 1 < attempts:
            time.sleep(policy.delay(k))
    return _finalize(record)


def _finalize(record: dict) -> dict:
    """Make infrastructure failures first-class records: a wedged/absent
    chip gets a structured ``backend_unavailable`` RESULT (the same schema
    slot a healthy run's bench JSON occupies) instead of ``result: null``,
    so downstream tooling plotting the bench trajectory can file the round
    as "chip was down" rather than a regression or a hole. Bench-side
    failures (``failed``) keep ``result: null`` — those ARE code problems."""
    if record["classification"] in ("wedged", "absent") \
            and record["result"] is None:
        record["backend_unavailable"] = True
        record["result"] = {
            "metric": "bench_unavailable",
            "value": None,
            "status": "backend_unavailable",
            "classification": record["classification"],
            "error": record["last_error"],
        }
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="retry/timeout/backoff wrapper for chip bench runs"
    )
    ap.add_argument("--attempts", type=int, default=3)
    ap.add_argument("--timeout", type=int, default=900,
                    help="per-attempt bench timeout [s]")
    ap.add_argument("--backoff", type=float, default=30.0,
                    help="initial inter-attempt backoff [s] (doubles)")
    ap.add_argument("--probe-timeout", type=int, default=60)
    ap.add_argument("--out", default=None,
                    help="also write the record to this JSON file")
    ap.add_argument("cmd", nargs="*", default=[],
                    help="bench command (default: python bench.py)")
    args = ap.parse_args(argv)
    cmd = args.cmd or [sys.executable, os.path.join(REPO, "bench.py")]

    record = run_with_retries(
        cmd, attempts=args.attempts, timeout_s=args.timeout,
        backoff_s=args.backoff, probe_timeout_s=args.probe_timeout,
    )
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out + "\n")
    return 0 if record["classification"] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
