#!/usr/bin/env python
"""Trace viewer/exporter: stitch ``trace_event`` rows into one
Chrome/Perfetto trace, and validate emitted trace files (the ci gate).

Thin CLI over ``tpu_aerial_transport/obs/trace.py`` (the span layer,
stitcher, Chrome converter, and critical-path accountant all live
there — loaded by file path so this tool never imports jax).

Usage:
  # One or more metrics jsonl files, or run DIRECTORIES (every *.jsonl
  # inside is read; a pods run dir's shard manifest names how many
  # process tracks make the trace complete):
  python tools/trace_view.py RUN_DIR_OR_JSONL... --out out.trace.json

  # Critical-path accounting (per-request queue/batch/device/harvest/
  # retry segments) as JSON:
  python tools/trace_view.py RUN.metrics.jsonl --critical-path

  # CI gate: structural validation of emitted trace files (well-formed
  # trace-event JSON, per-track monotone non-overlapping slices, every
  # span's parent present); exit 1 on any violation:
  python tools/trace_view.py --validate artifacts/*.trace.json

Load the emitted file at https://ui.perfetto.dev (or chrome://tracing):
one process row per track (server process / pods process), one thread
row per span name.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# By-path load (the resilience.backend discipline): the span layer is
# stdlib-only, and importing it as a package submodule would execute
# tpu_aerial_transport.obs.__init__ — which pulls jax. A trace viewer
# must work on hosts where importing jax is the hazard being traced.
_spec = importlib.util.spec_from_file_location(
    "tat_obs_trace",
    os.path.join(_REPO, "tpu_aerial_transport", "obs", "trace.py"),
)
trace_mod = importlib.util.module_from_spec(_spec)
# Registered BEFORE exec: dataclass processing under `from __future__
# import annotations` resolves the defining module via sys.modules.
sys.modules["tat_obs_trace"] = trace_mod
_spec.loader.exec_module(trace_mod)


def collect_rows(paths: list[str]) -> list[dict]:
    """Stitched trace rows from a mix of jsonl files and run dirs."""
    rows: list[dict] = []
    for path in paths:
        if os.path.isdir(path):
            rows.extend(trace_mod.stitch_run_dir(path))
        else:
            rows.extend(
                trace_mod.trace_rows(trace_mod._read_jsonl(path))
            )
    # stitch() is idempotent on already-stitched rows (the t0/t1 fields
    # are recomputed from the same anchors), so one final pass unifies
    # the mixed-source case.
    return trace_mod.stitch(rows)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("paths", nargs="+",
                    metavar="RUN_DIR_OR_JSONL_OR_TRACE")
    ap.add_argument("--out", default="",
                    help="write Chrome/Perfetto trace-event JSON here")
    ap.add_argument("--critical-path", action="store_true",
                    help="print the per-request critical-path "
                         "decomposition as JSON")
    ap.add_argument("--validate", action="store_true",
                    help="paths are emitted *.trace.json files: "
                         "structural validation only (ci gate), exit 1 "
                         "on any violation")
    args = ap.parse_args()

    if args.validate:
        failed = False
        for path in args.paths:
            errs = trace_mod.validate_trace_file(path)
            if errs:
                failed = True
                print(f"{path}: {len(errs)} violation(s)",
                      file=sys.stderr)
                for e in errs[:20]:
                    print(f"  {e}", file=sys.stderr)
            else:
                print(f"{path}: OK")
        return 1 if failed else 0

    rows = collect_rows(args.paths)
    if not rows:
        print("no trace_event rows found (tracing off, or wrong files?)",
              file=sys.stderr)
        return 1
    summary = {
        "rows": len(rows),
        "tracks": sorted({r.get("track", "?") for r in rows}),
        "traces": len({r["trace_id"] for r in rows}),
    }
    if args.out:
        obj = trace_mod.write_chrome_trace(args.out, rows)
        errs = trace_mod.validate_chrome_trace(obj)
        if errs:  # never publish a trace the ci gate would reject.
            for e in errs[:20]:
                print(f"  {e}", file=sys.stderr)
            return 1
        summary["out"] = args.out
        summary["events"] = len(obj["traceEvents"])
    if args.critical_path:
        summary["critical_path"] = trace_mod.critical_path(rows)
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
