#!/usr/bin/env python
"""AOT bundle CLI: build / check / diff / serve over the entrypoint
registry's compilation artifacts (``tpu_aerial_transport/aot/``).

Usage::

    python tools/aot_bundle.py build --out artifacts/aot/cpu \\
        [--platform cpu|tpu] [--entry NAME ...] [--manifest-only] \\
        [--no-exec] [--batch-buckets 8,64]
    python tools/aot_bundle.py check BUNDLE_DIR      # CI drift gate
    python tools/aot_bundle.py diff BUNDLE_DIR       # same, report-only
    python tools/aot_bundle.py serve --entry NAME --mode bundled|cached|cold
        [--bundle DIR] [--cache-dir D] [--expect-zero-compile]

``check`` diffs the bundle's coverage (entry names + shape signatures)
against the LIVE ``analysis.entrypoints`` registry and exits 1 on drift —
a new/changed entrypoint cannot land without a bundle rebuild
(``tools/ci_check.sh`` runs it against the in-tree CPU coverage manifest).
Signatures come from ``make_args`` avals only, so the gate never lowers
or compiles anything.

``serve`` is the cold-start measurement/proof driver: a FRESH process
executes one registered entrypoint end-to-end and reports
time-to-first-step plus how many traces / MLIR lowerings / XLA backend
compiles the process paid (counted via jax's monitoring events — the
whole-process flavor of the TC101 cache-miss counting). ``--mode
bundled`` with ``--expect-zero-compile`` exits 3 unless all three
counters are zero; ``bench.py --sweep``'s ``coldstart_*`` A/B cells and
tests/test_aot.py both drive it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _counters():
    """Register jax monitoring listeners and return the live counter dict.
    Must run before any compilation; jax import itself compiles nothing."""
    from jax._src import monitoring

    counts = {"traces": 0, "lowerings": 0, "backend_compiles": 0,
              "cache_hits": 0}

    def on_duration(event, duration, **kw):
        del duration, kw
        if event.endswith("jaxpr_trace_duration"):
            counts["traces"] += 1
        elif event.endswith("jaxpr_to_mlir_module_duration"):
            counts["lowerings"] += 1
        elif event.endswith("backend_compile_duration"):
            counts["backend_compiles"] += 1
        elif event.endswith("compile_time_saved_sec"):
            counts["cache_hits"] += 1

    monitoring.register_event_duration_secs_listener(on_duration)
    return counts


def cmd_build(args) -> int:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    from tpu_aerial_transport.aot import bundle as bundle_mod

    buckets = [int(b) for b in args.batch_buckets.split(",") if b]
    t0 = time.perf_counter()
    manifest = bundle_mod.build_bundle(
        args.out,
        platform=args.platform,
        names=args.entry or None,
        exec_artifacts=not args.no_exec,
        manifest_only=args.manifest_only,
        batch_buckets=buckets,
        progress=lambda name: print(f"# building {name}", flush=True),
    )
    n_exec = sum(
        1 for e in manifest["entries"].values()
        for v in e["variants"] if "exec" in v.get("artifacts", {})
    )
    print(json.dumps({
        "bundle": args.out,
        "platform": manifest["platform"],
        "entries": len(manifest["entries"]),
        "skipped": len(manifest["skipped"]),
        "exec_variants": n_exec,
        "manifest_only": manifest["manifest_only"],
        "build_s": round(time.perf_counter() - t0, 1),
    }))
    return 0


def _diff(bundle_dir: str) -> dict:
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    from tpu_aerial_transport.aot import bundle as bundle_mod

    manifest = bundle_mod.read_manifest(bundle_dir)
    return bundle_mod.coverage_diff(manifest)


def cmd_check(args) -> int:
    diff = _diff(args.bundle)
    if diff["ok"]:
        print(f"aot_bundle check: OK ({args.bundle} covers the registry)")
        return 0
    for kind in ("missing", "stale", "changed", "uncovered_skips"):
        for item in diff[kind]:
            print(f"aot_bundle check [{kind}]: {item}")
    print(
        "aot_bundle check: DRIFT — the entrypoint registry and the bundle "
        f"disagree; rebuild with: python tools/aot_bundle.py build --out "
        f"{args.bundle}" + (" --manifest-only" if args.manifest_hint else "")
    )
    return 1


def cmd_diff(args) -> int:
    print(json.dumps(_diff(args.bundle), indent=1))
    return 0


def cmd_serve(args) -> int:
    counts = _counters()  # before anything can compile.

    from tpu_aerial_transport.utils.platform import (
        enable_persistent_cache,
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    if args.mode == "cached":
        cache_dir = enable_persistent_cache(args.cache_dir or None)
    else:
        cache_dir = None  # bundled needs none; cold measures the pre-cache
        # world even when TAT_XLA_CACHE_DIR is exported.

    # Time-to-first-step clock starts HERE — AFTER the interpreter + jax
    # import (a replica pays those once at deploy, before any request
    # arrives, and _counters() must register against jax's monitoring
    # before anything can compile), but before backend init, bundle load,
    # input construction, and dispatch. A cold process's first step pays
    # all of those (the registry's make_args alone runs hundreds of eager
    # one-op compiles); the bundled path replaces every piece with
    # deserialization. Timing only the final call would hide exactly the
    # cost this subsystem removes; the bench cell's ``process_wall_s``
    # records the whole-process wall time (import included) alongside.
    t0 = time.perf_counter()

    import jax

    from tpu_aerial_transport.aot import loader as loader_mod

    bundle = loader_mod.load_bundle(args.bundle) if args.bundle else None

    # Inputs come from the manifest's recorded avals (host numpy, no
    # compiles) so every mode sees identical data; without a bundle the
    # registry's make_args builds them (jit modes only).
    if bundle is not None:
        call_args = bundle.probe_args(args.entry)
    else:
        from tpu_aerial_transport.analysis import contracts

        _, make_args = contracts.REGISTRY[args.entry].build()
        call_args = make_args()

    out = {"entry": args.entry, "mode": args.mode,
           "platform": jax.default_backend(),
           **({"cache_dir": cache_dir} if cache_dir else {})}
    t_serve = time.perf_counter()
    if args.mode == "bundled":
        result, rung = loader_mod.serve_entry(bundle, args.entry, call_args)
    else:
        from tpu_aerial_transport.analysis import contracts

        fn, _ = contracts.REGISTRY[args.entry].build()
        result, rung = loader_mod.serve_entry(
            None, args.entry, call_args, jit_fallback=fn
        )
    jax.block_until_ready(result)
    now = time.perf_counter()
    out["ttfs_s"] = round(now - t0, 4)
    out["serve_s"] = round(now - t_serve, 4)
    out["rung"] = rung
    out.update(counts)
    print(json.dumps(out), flush=True)
    if args.expect_zero_compile:
        paid = {k: counts[k] for k in
                ("traces", "lowerings", "backend_compiles") if counts[k]}
        if paid:
            print(f"aot_bundle serve: NOT zero-compile: {paid}",
                  file=sys.stderr)
            return 3
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="build a bundle from the registry")
    b.add_argument("--out", required=True)
    b.add_argument("--platform", default=None,
                   help="target platform (default: this host's backend); "
                        "a non-local platform builds export artifacts only")
    b.add_argument("--entry", action="append", default=[],
                   help="restrict to these registry entries (repeatable)")
    b.add_argument("--manifest-only", action="store_true",
                   help="record coverage (names + signatures) without "
                        "lowering — the cheap in-tree CI artifact")
    b.add_argument("--no-exec", action="store_true",
                   help="skip the serialized-executable artifacts")
    b.add_argument("--batch-buckets", default="",
                   help="comma-separated scenario-batch bucket sizes for "
                        "the batched entries (bucket_dim grid)")
    b.set_defaults(fn=cmd_build)

    c = sub.add_parser("check", help="fail on registry/bundle drift")
    c.add_argument("bundle")
    c.add_argument("--manifest-hint", action="store_true",
                   help="phrase the rebuild hint for a manifest-only bundle")
    c.set_defaults(fn=cmd_check)

    d = sub.add_parser("diff", help="report registry/bundle drift as JSON")
    d.add_argument("bundle")
    d.set_defaults(fn=cmd_diff)

    s = sub.add_parser("serve", help="cold-start measurement/proof driver")
    s.add_argument("--entry", required=True)
    s.add_argument("--mode", required=True,
                   choices=["bundled", "cached", "cold"])
    s.add_argument("--bundle", default="")
    s.add_argument("--cache-dir", default="")
    s.add_argument("--expect-zero-compile", action="store_true",
                   help="exit 3 unless traces == lowerings == "
                        "backend_compiles == 0")
    s.set_defaults(fn=cmd_serve)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
