"""Component-split profile of the n=64 batched DD MPC step (VERDICT r4
item 4): where does the step time go — QP build, KKT-operator build, the
per-iteration conic solves, or the 6n-dim quasi-Newton dual machinery?

Methodology (scan-amortized, same conventions as bench.py): each variant is
the FULL batched MPC step with one knob moved, timed as a fixed-iteration
rollout; differencing isolates the component. Runs on whatever backend JAX
resolves (CPU relative structure transfers to TPU for the vector path;
absolute numbers do not — rerun on chip for the record).

Usage: JAX_PLATFORMS=cpu python tools/profile_dd64.py [--n 64] [--batch 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, *args, reps=3, n_steps=6):
    jitted = jax.jit(fn, static_argnames="n_steps")
    out = jitted(*args, n_steps=n_steps)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jitted(*args, n_steps=n_steps)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)) / n_steps * 1e3  # ms / MPC step


def build_step(n, batch, max_iter, inner_iters, fixed=True, inner_tol=0.0):
    import bench

    mpc_step, cs0, state0 = bench.make_mpc_step(
        "dd", n, max_iter=max_iter, inner_iters=inner_iters,
        force_fixed_iters=fixed, inner_tol=inner_tol,
    )
    states = bench._scenario_batch(state0, batch)
    css = jax.vmap(lambda _: cs0)(jnp.arange(batch))
    vstep = jax.vmap(mpc_step)

    def roll(css, states, n_steps):
        def body(carry, _):
            cs, s = carry
            cs, s, _ = vstep(cs, s)
            return (cs, s), None

        return jax.lax.scan(body, (css, states), None, length=n_steps)[0]

    return roll, css, states


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()
    n, batch = args.n, args.batch

    res = {"platform": jax.devices()[0].platform, "n": n, "batch": batch}

    # (1) Fixed 8 outer x 40 inner (the bench operating point's iteration
    #     shape) vs 8 x 20: differencing gives the pure inner-ADMM cost.
    t_8x40 = timed(*build_step(n, batch, max_iter=7, inner_iters=40))
    t_8x20 = timed(*build_step(n, batch, max_iter=7, inner_iters=20))
    res["step_ms_8outer_40inner"] = t_8x40
    res["step_ms_8outer_20inner"] = t_8x20
    res["ms_per_inner_iter_x8outer"] = (t_8x40 - t_8x20) / 20
    # per single inner ADMM iteration across the whole batch (8 outer iters
    # each run `inner` of them):
    res["ms_per_single_inner_iter"] = (t_8x40 - t_8x20) / 20 / 8

    # (2) Outer-iteration overhead beyond the solves: 16 outer vs 8 outer at
    #     fixed inner=20 gives (solve + QN + consensus) per outer; subtract
    #     the solve part from (1).
    t_16x20 = timed(*build_step(n, batch, max_iter=15, inner_iters=20))
    res["step_ms_16outer_20inner"] = t_16x20
    per_outer = (t_16x20 - t_8x20) / 8
    res["ms_per_outer_iter_at_inner20"] = per_outer
    solve_per_outer = res["ms_per_single_inner_iter"] * 20
    res["ms_per_outer_qn_and_consensus"] = per_outer - solve_per_outer

    # (3) Fixed per-step work (QP build, kkt_operator, env query, low-level
    #     + physics substeps): extrapolate to zero outer iterations.
    res["ms_fixed_per_step"] = t_8x20 - 8 * per_outer

    # (4) Adaptive run (real tolerances) for the actual operating point.
    roll, css, states = build_step(n, batch, max_iter=20, inner_iters=40,
                                   fixed=False)
    res["step_ms_adaptive"] = timed(roll, css, states)

    # (5) Adaptive + tolerance-chunked inner solves (inner_tol): warm-started
    #     agent QPs stop their ADMM chunks at 2e-3 residual instead of always
    #     burning the full 40-iteration budget.
    roll, css, states = build_step(n, batch, max_iter=20, inner_iters=40,
                                   fixed=False, inner_tol=2e-3)
    res["step_ms_adaptive_inner_tol"] = timed(roll, css, states)
    res["inner_tol_speedup"] = (
        res["step_ms_adaptive"] / res["step_ms_adaptive_inner_tol"]
    )

    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
