#!/usr/bin/env python
"""Live fleet console: rolling windows + SLO burn rates + alert state.

Tails every ``*.metrics.jsonl`` under the given paths (files or
directories, discovered live as replicas boot), merges the streams into
1s/10s/60s rolling windows per tenant, and evaluates the declarative
SLO engine (``obs.live``) each refresh — per-tenant throughput, miss /
rejection / cache-hit rates, latency percentiles, multi-window burn
rates, and the firing-alert set, all from the journaled wall-clock
``ts`` domain (a replayed file renders exactly what the live run saw).

Usage:
  python tools/fleet_console.py artifacts/fleet/ [--refresh 1]
  python tools/fleet_console.py RUN.metrics.jsonl --once --json
  python tools/fleet_console.py artifacts/ --slo p99:step_latency:0.99:threshold_s=0.5

``--once`` drains everything currently on disk, renders one frame, and
exits — the CI mode: its numbers are REQUIRED to match a post-hoc
recompute from ``jsonl_read`` exactly (pinned by tests/test_live.py).
Exit 1 when ``--once`` ends with alerts still firing, else 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpu_aerial_transport.obs import live as live_mod  # noqa: E402


def build_engine(args) -> tuple:
    """(FleetTailer, SLOEngine) from parsed args."""
    specs = None
    if args.slo:
        specs = tuple(live_mod.parse_slo_spec(s) for s in args.slo)
    tailer = live_mod.FleetTailer(args.paths)
    engine = live_mod.SLOEngine(specs)
    return tailer, engine


def drain(tailer, engine) -> int:
    """Poll until the tailer reports nothing new; returns events read."""
    total = 0
    while True:
        n = engine.ingest_all(tailer.poll())
        total += n
        if n == 0:
            return total


def frame(engine, windows=None) -> dict:
    """One machine-readable console frame (the --json payload)."""
    windows = live_mod.CONSOLE_WINDOWS if windows is None else windows
    engine.evaluate()
    return {
        "now": engine.windows.latest_ts,
        "groups": [list(g) for g in engine.windows.groups()],
        "windows": {str(w): engine.windows.rates(w) for w in windows},
        "slo": engine.snapshot(),
    }


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render(fr: dict) -> None:
    now = fr["now"]
    print(f"fleet console @ ts={_fmt(now)}  "
          f"groups(tenant,family,replica)={len(fr['groups'])}")
    for w, by_tenant in fr["windows"].items():
        print(f"\n-- window {w}s --")
        if not by_tenant:
            print("  (no traffic)")
            continue
        head = (f"  {'tenant':<12} {'subm':>6} {'done':>6} {'rej':>5} "
                f"{'miss':>5} {'steps':>6} {'p50':>8} {'p99':>8} "
                f"{'miss%':>7} {'rej%':>7} {'hit%':>7}")
        print(head)
        for tenant, row in sorted(by_tenant.items()):
            lat = row["latency"]
            pct = (lambda r: "—" if r is None else f"{100 * r:.1f}")
            print(f"  {tenant:<12} {row.get('submitted', 0):>6} "
                  f"{row.get('completed', 0):>6} "
                  f"{row.get('rejected', 0):>5} "
                  f"{row.get('missed', 0):>5} "
                  f"{row.get('steps', 0):>6} "
                  f"{_fmt(lat['p50']):>8} {_fmt(lat['p99']):>8} "
                  f"{pct(row['miss_rate']):>7} "
                  f"{pct(row['rejection_rate']):>7} "
                  f"{pct(row['cache_hit_rate']):>7}")
    slo = fr["slo"]
    print("\n-- slo burn rates (fast/slow) --")
    if not slo["burn_rates"]:
        print("  (no traffic)")
    for key, burns in sorted(slo["burn_rates"].items()):
        print(f"  {key:<28} {_fmt(burns['fast']):>8} "
              f"{_fmt(burns['slow']):>8}")
    if slo["firing"]:
        print(f"\nALERTS FIRING: {', '.join(slo['firing'])}")
    else:
        print("\nalerts: none firing")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="+", metavar="FILE_OR_DIR",
                    help="metrics jsonl files and/or directories to "
                         "scan for *.metrics.jsonl")
    ap.add_argument("--once", action="store_true",
                    help="drain current contents, render one frame, "
                         "exit (nonzero when alerts are firing)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable frames instead of tables")
    ap.add_argument("--refresh", type=float, default=None,
                    help="live refresh period in seconds "
                         "(TAT_CONSOLE_REFRESH_S overrides; default 1)")
    ap.add_argument("--slo", action="append", default=None,
                    metavar="NAME:METRIC:OBJECTIVE[:k=v...]",
                    help="SLO spec (repeatable; default: the standard "
                         "step_p99/miss_rate/rejection trio)")
    ap.add_argument("--rounds", type=int, default=None,
                    help="stop live mode after N refreshes (tests)")
    args = ap.parse_args()

    tailer, engine = build_engine(args)
    if args.once:
        drain(tailer, engine)
        fr = frame(engine)
        if args.json:
            print(json.dumps(fr, indent=1))
        else:
            render(fr)
        return 1 if fr["slo"]["firing"] else 0

    refresh = live_mod.resolve_refresh_s(args.refresh)
    rounds = 0
    while True:
        engine.ingest_all(tailer.poll())
        fr = frame(engine)
        if args.json:
            print(json.dumps(fr))
        else:
            print("\033[2J\033[H", end="")  # clear screen, home cursor.
            render(fr)
        rounds += 1
        if args.rounds is not None and rounds >= args.rounds:
            return 1 if fr["slo"]["firing"] else 0
        time.sleep(refresh)


if __name__ == "__main__":
    raise SystemExit(main())
