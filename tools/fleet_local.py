#!/usr/bin/env python
"""Localhost serving-fleet harness: ONE admission front + N replica
``ScenarioServer`` worker processes (the ``tools/pods_local.py``
own-session / group-killable / parent-pid-watch discipline), wired over
durable jsonl channels so every hop survives a SIGKILL:

- ``r{i}/inbox.jsonl``   — front -> replica ops (submit/cancel/wedge/
  inject_error/shutdown), replayed idempotently on replica restart;
- ``r{i}/replica.metrics.jsonl`` — replica -> front heartbeats
  (``fleet_event`` rows), serving/trace events (per-replica ``r{i}``
  span track);
- ``r{i}/outbox.jsonl``  — replica -> front results (request_id +
  status + digest); the front is completion-authoritative (first
  result wins, duplicates dropped + counted);
- ``r{i}/run/``          — the replica's PR-4 journal + boundary
  snapshots; a respawned replica RESUMES it (durability path) while
  the front re-dispatches its in-flight work to healthy replicas
  (latency path) — digests agree bit-for-bit by the lane-independence
  contract, so first-wins dedup is safe.

The parent runs the :class:`serving.fleet.ReplicaSupervisor` (heartbeat
leases + classified-error breaker + bounded-backoff restarts +
quarantine) and the :class:`serving.fleet.FleetFront` ((family, bucket)
consistent-hash routing + per-tenant admission + failover re-dispatch).
``--chaos`` drives a seeded :class:`FleetFaultPlan` — the acceptance
storm SIGKILLs/wedges replicas mid-batch and still exits 0 with every
non-rejected request's digest equal to the fault-free run's.

Hosts that cannot run multiple replicas (1 CPU core) skip with a
written reason instead of flaking; ``--force-multi`` overrides (the
replicas are independent CPU processes with generous leases — unlike
the pods gloo rendezvous, time-slicing them is slow but sound).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpu_aerial_transport.obs import export as export_mod  # noqa: E402
from tpu_aerial_transport.serving import fleet as fleet_mod  # noqa: E402

HEARTBEAT_FRACTION = 0.4  # emit cadence as a fraction of the lease.


def _read_new_lines(path: str, offset: int) -> tuple[list[dict], int]:
    """Complete (newline-terminated) JSON lines past ``offset``; a torn
    tail stays unread until its newline lands (the jsonl_append fsync
    contract makes line-grained tailing sound across processes)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(offset)
            blob = fh.read()
    except FileNotFoundError:
        return [], offset
    if not blob:
        return [], offset
    keep = blob.rfind(b"\n") + 1
    rows = []
    for line in blob[:keep].splitlines():
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows, offset + keep


# ----------------------------------------------------------------------
# Replica worker.
# ----------------------------------------------------------------------

def _orphan_watchdog() -> None:
    """Replicas run in their own sessions (group-killability), so a
    parent crash does not reap them — watch the parent pid and exit on
    reparent (the pods_local rule)."""
    parent = os.getppid()

    def watch():
        while True:
            time.sleep(2.0)
            if os.getppid() != parent:
                os._exit(17)

    threading.Thread(target=watch, daemon=True).start()


class _Wedge:
    """Replica-side wedge clamp: while wedged, the main loop stalls AND
    the heartbeat thread goes silent — exactly the failure the
    supervisor's lease machine must catch."""

    def __init__(self):
        self.until = 0.0

    def set(self, seconds: float) -> None:
        self.until = time.monotonic() + seconds

    @property
    def active(self) -> bool:
        return time.monotonic() < self.until


def run_worker(args) -> int:
    _orphan_watchdog()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    from tpu_aerial_transport.obs import trace as trace_mod
    from tpu_aerial_transport.serving import server as server_mod

    rdir = args.dir
    rid = args.replica_id
    inbox = os.path.join(rdir, "inbox.jsonl")
    outbox = os.path.join(rdir, "outbox.jsonl")
    run_dir = os.path.join(rdir, "run")
    writer = export_mod.MetricsWriter(
        os.path.join(rdir, "replica.metrics.jsonl"),
        meta={"replica": rid, "pid": os.getpid()},
    )
    wedge = _Wedge()
    hb_seq = [0]

    def heartbeats():
        period = max(0.05, args.lease * HEARTBEAT_FRACTION)
        while True:
            if not wedge.active:
                hb_seq[0] += 1
                writer.emit("fleet_event", kind="heartbeat", replica=rid,
                            seq=hb_seq[0], pid=os.getpid())
            time.sleep(period)

    # Heartbeats start BEFORE server construction: a slow jax boot must
    # read as "starting", not "dead on arrival".
    threading.Thread(target=heartbeats, daemon=True).start()

    tracer = trace_mod.Tracer(writer, track=f"r{rid}")
    kw = dict(
        families=[f for f in args.families.split(",") if f],
        buckets=tuple(int(b) for b in args.buckets.split(",") if b),
        capacity=args.capacity,
        bundle=args.bundle or None, require_bundle=args.require_bundle,
        metrics=writer, tracer=tracer,
    )
    journal = os.path.join(run_dir, server_mod.SERVING_JOURNAL)
    if os.path.exists(journal):
        # Respawn: restore batches from boundary snapshots, re-admit the
        # journaled queue remainder (the PR-4 durability path).
        server = server_mod.ScenarioServer.resume(run_dir, **kw)
    else:
        server = server_mod.ScenarioServer(run_dir=run_dir, **kw)

    cancelled: set[str] = set()
    reported: set[str] = set()
    shutdown = [False]
    offset = 0

    def apply_op(op: dict, replay: bool) -> None:
        name = op.get("op")
        if name == "submit":
            from tpu_aerial_transport.serving.queue import ScenarioRequest

            req = ScenarioRequest.from_json(op["request"])
            # Idempotent under inbox replay AND resume restore.
            if (req.request_id in server.tickets
                    or req.request_id in server.done_requests):
                return
            server.submit(req)
        elif name == "cancel":
            # Don't report a result the front already failed over —
            # a lost cancel only costs a deduped duplicate downstream.
            cancelled.add(op["request_id"])
        elif replay:
            # wedge/inject_error/shutdown are live-only: replaying a
            # pre-crash wedge (or a shutdown meant for the old pid)
            # against the respawn would be a self-inflicted fault.
            return
        elif name == "wedge":
            wedge.set(float(op.get("seconds", 2.0)))
        elif name == "inject_error":
            # Surface a classified BackendError kind upward; the parent
            # feeds the supervisor (infra kinds strike the breaker,
            # compile_error never does).
            writer.emit("fleet_event", kind="replica_error", replica=rid,
                        error_kind=op.get("kind", "device_crash"),
                        detail="injected")
        elif name == "shutdown":
            shutdown[0] = True

    # Boot replay: everything already in the inbox (ops addressed to a
    # pre-crash incarnation) — submits/cancels only.
    rows, offset = _read_new_lines(inbox, offset)
    for op in rows:
        apply_op(op, replay=True)

    while True:
        rows, offset = _read_new_lines(inbox, offset)
        for op in rows:
            apply_op(op, replay=False)
        if wedge.active:
            time.sleep(0.05)
            continue
        worked = server.pump() if server.has_work() else False
        for req_id, ticket in list(server.tickets.items()):
            if not ticket.done or req_id in reported:
                continue
            reported.add(req_id)
            if req_id in cancelled:
                continue
            row = {"request_id": req_id, "status": ticket.status,
                   "replica": rid, "steps_served": ticket.steps_served}
            if ticket.reason:
                row["reason"] = ticket.reason
            if ticket.result is not None:
                row["digest"] = fleet_mod.result_digest(ticket.result)
            export_mod.jsonl_append(outbox, row)
        if shutdown[0] and not server.has_work():
            return 0
        if not worked:
            time.sleep(0.02)


# ----------------------------------------------------------------------
# Parent: supervisor + front + chaos.
# ----------------------------------------------------------------------

def _strip_force_flag(flags: str) -> str:
    return " ".join(
        tok for tok in flags.split()
        if not tok.startswith("--xla_force_host_platform_device_count")
    ).strip()


class _Replica:
    """Parent-side handle: process + channel offsets + kill bookkeeping."""

    def __init__(self, rid: int, rdir: str):
        self.rid = rid
        self.rdir = rdir
        self.proc: subprocess.Popen | None = None
        self.metrics_offset = 0
        self.outbox_offset = 0
        self.exit_seen = True  # no process yet.

    @property
    def inbox(self) -> str:
        return os.path.join(self.rdir, "inbox.jsonl")

    @property
    def metrics(self) -> str:
        return os.path.join(self.rdir, "replica.metrics.jsonl")

    @property
    def outbox(self) -> str:
        return os.path.join(self.rdir, "outbox.jsonl")

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def kill(self, sig=signal.SIGKILL) -> None:
        if self.proc is not None and self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                self.proc.kill()


def _spawn_replica(rep: _Replica, args) -> None:
    env = dict(os.environ)
    env["XLA_FLAGS"] = _strip_force_flag(env.get("XLA_FLAGS", ""))
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--replica-id", str(rep.rid), "--dir", rep.rdir,
        "--families", args.families, "--buckets", args.buckets,
        "--capacity", str(args.capacity), "--lease", str(args.lease),
    ] + (["--bundle", args.bundle] if args.bundle else []) \
      + (["--require-bundle"] if args.require_bundle else [])
    # stderr to a file, not a pipe: nobody drains replica pipes, and a
    # chatty boot (XLA warnings) must not wedge the replica on a full
    # pipe buffer. Append mode keeps the pre-crash tail for post-mortem.
    with open(os.path.join(rep.rdir, "stderr.log"), "ab") as err:
        rep.proc = subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=err,
            env=env, start_new_session=True, cwd=_REPO,
        )
    rep.exit_seen = False


def make_fleet_stream(n_requests: int, families: list[str],
                      chunk_lens: dict, tenants: list[str], seed: int):
    """Deterministic mixed-tenant request stream (the serve_scenarios
    stream generator + a seeded tenant column): same seed => same
    stream, the chaos-vs-fault-free digest comparison's precondition."""
    import numpy as np

    from tpu_aerial_transport.serving.queue import ScenarioRequest

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_requests):
        fam = families[int(rng.integers(len(families)))]
        horizon = int(rng.integers(1, 4)) * chunk_lens[fam]
        tenant = tenants[int(rng.integers(len(tenants)))]
        out.append(ScenarioRequest(
            family=fam, horizon=horizon,
            x0=tuple(float(v) for v in rng.normal(0, 1.0, 3)),
            v0=(0.1, 0.0, 0.0),
            request_id=f"req{i:05d}", tenant=tenant,
        ))
    return out


def parse_tenants(spec: str) -> dict:
    """``name:rate=R,burst=B,weight=W,priority=P;name2:...`` ->
    {name: TenantPolicy}; unknown keys are an error (a typo'd policy
    must not silently admit everything)."""
    from tpu_aerial_transport.serving.queue import TenantPolicy

    out = {}
    for chunk in (c.strip() for c in (spec or "").split(";")):
        if not chunk:
            continue
        name, _, body = chunk.partition(":")
        kw: dict = {}
        for item in (i for i in body.split(",") if i):
            key, _, val = item.partition("=")
            if key == "rate":
                kw["rate_per_s"] = float(val)
            elif key == "burst":
                kw["burst"] = int(val)
            elif key == "weight":
                kw["weight"] = float(val)
            elif key == "priority":
                kw["priority"] = int(val)
            else:
                raise SystemExit(f"unknown tenant policy key {key!r}")
        out[name] = TenantPolicy(**kw)
    return out


def run_fleet(args) -> tuple[dict, int]:
    """Drive the whole storm. Returns (summary, exit code) so
    examples/serve_fleet.py can reuse the driver verbatim."""
    from tpu_aerial_transport.obs import trace as trace_lib
    from tpu_aerial_transport.serving import batcher

    out_dir = args.out_dir
    os.makedirs(out_dir, exist_ok=True)
    # A run's channel files (inboxes, outboxes, metrics, journals) are
    # strictly per-run state: a RE-used out_dir must not leak a prior
    # run's ops/results into this one (same seed -> same request_ids ->
    # stale outbox rows would resolve fresh tickets). Within-run resume
    # (replica respawn -> journal replay) is untouched — the wipe
    # happens once, before any replica spawns. Append-only MetricsWriter
    # files are removed too so run_health's append-dedup stays an
    # explicit opt-in (cat two runs together), not an accident.
    for i in range(args.replicas):
        shutil.rmtree(os.path.join(out_dir, f"r{i}"), ignore_errors=True)
    for stale in ("front.metrics.jsonl", "fleet.metrics.jsonl"):
        with contextlib.suppress(FileNotFoundError):
            os.remove(os.path.join(out_dir, stale))
    families = [f for f in args.families.split(",") if f]
    buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    chunk_lens = {
        f: batcher.CANONICAL_FAMILIES[f].chunk_len for f in families
    }
    tenants = parse_tenants(args.tenants)
    tenant_names = sorted(tenants) or ["default"]

    plan = fleet_mod.FleetFaultPlan()
    if args.chaos:
        if args.chaos.startswith("seeded:"):
            plan = fleet_mod.FleetFaultPlan.seeded(
                int(args.chaos.split(":", 1)[1]), args.replicas,
                t_span=args.chaos_span,
            )
        else:
            plan = fleet_mod.FleetFaultPlan.parse(args.chaos)
    elif os.environ.get(fleet_mod.FLEET_FAULTS_ENV):
        plan = fleet_mod.FleetFaultPlan.from_env()

    writer = export_mod.MetricsWriter(
        os.path.join(out_dir, "front.metrics.jsonl"),
        meta={"role": "front", "replicas": args.replicas,
              "chaos": plan.to_spec()},
    )
    tracer = trace_lib.Tracer(writer, track="front")
    supervisor = fleet_mod.ReplicaSupervisor(
        list(range(args.replicas)),
        lease_s=args.lease, boot_grace_s=args.boot_grace,
        quarantine_after=args.quarantine_after, emit=writer,
    )
    replicas = {
        i: _Replica(i, os.path.join(out_dir, f"r{i}"))
        for i in range(args.replicas)
    }
    for rep in replicas.values():
        os.makedirs(os.path.join(rep.rdir, "run"), exist_ok=True)

    front = fleet_mod.FleetFront(
        list(range(args.replicas)),
        lambda fam: chunk_lens.get(fam),
        send=lambda rid, op: export_mod.jsonl_append(
            replicas[rid].inbox, op
        ),
        buckets=buckets, capacity=args.capacity, tenants=tenants,
        supervisor=supervisor, metrics=writer, tracer=tracer,
    )

    for rep in replicas.values():
        _spawn_replica(rep, args)

    stream = make_fleet_stream(args.requests, families, chunk_lens,
                               tenant_names, args.seed)
    import numpy as np

    arrival_rng = np.random.default_rng(args.seed + 1)
    rng_wait = (1.0 / args.poisson_rate) if args.poisson_rate else 0.0

    def execute(action: str, rid: int) -> None:
        rep = replicas[rid]
        if action == "kill":
            rep.kill()
            rep.exit_seen = True  # this exit is ours, not news.
        elif action == "failover":
            front.failover(rid)
        elif action == "spawn":
            _spawn_replica(rep, args)
        elif action == "quarantine":
            pass  # terminal: no respawn, ring routes around it.

    t0 = time.monotonic()
    chaos_t = 0.0
    next_due = t0
    deadline = t0 + args.timeout
    rc = 0
    while True:
        now = time.monotonic()
        # Scheduled chaos (storm-relative clock).
        for fault in plan.due(chaos_t, now - t0):
            rep = replicas[fault.replica]
            if fault.action == "sigkill":
                rep.kill(signal.SIGKILL)
            elif fault.action == "sigterm":
                rep.kill(signal.SIGTERM)
            elif fault.action == "wedge":
                front.send(fault.replica, {
                    "op": "wedge",
                    "seconds": float(fault.arg or 2.0),
                })
            elif fault.action == "error":
                front.send(fault.replica, {
                    "op": "inject_error",
                    "kind": fault.arg or "device_crash",
                })
        chaos_t = now - t0

        # Arrivals (Poisson or all up front) + routing.
        while stream and (not rng_wait or time.monotonic() >= next_due):
            front.submit(stream.pop(0))
            if rng_wait:
                next_due += arrival_rng.exponential(rng_wait)
        front.pump()

        # Replica -> front channels.
        for rep in replicas.values():
            rows, rep.metrics_offset = _read_new_lines(
                rep.metrics, rep.metrics_offset
            )
            for row in rows:
                if row.get("event") != "fleet_event":
                    continue
                if row.get("kind") == "heartbeat":
                    # Only the CURRENT incarnation's pulse counts — a
                    # pre-kill row read post-kill must not resurrect a
                    # replica the supervisor already declared down.
                    if row.get("pid") == rep.pid and not rep.exit_seen:
                        supervisor.heartbeat(rep.rid)
                elif row.get("kind") == "replica_error":
                    for act in supervisor.report_error(
                        rep.rid, row.get("error_kind", ""),
                        row.get("detail", ""),
                    ):
                        execute(*act)
            rows, rep.outbox_offset = _read_new_lines(
                rep.outbox, rep.outbox_offset
            )
            for row in rows:
                front.deliver_result(row)

        # Unexpected exits (chaos SIGKILL detection beats lease expiry).
        for rep in replicas.values():
            if (not rep.exit_seen and rep.proc is not None
                    and rep.proc.poll() is not None):
                rep.exit_seen = True
                for act in supervisor.notify_exit(
                    rep.rid, rep.proc.returncode
                ):
                    execute(*act)

        for act in supervisor.tick():
            execute(*act)

        if not stream and not front.unresolved():
            break
        if time.monotonic() > deadline:
            rc = 1
            break
        time.sleep(args.poll)

    # Drain: graceful shutdowns, then group-kill stragglers.
    for rep in replicas.values():
        front.send(rep.rid, {"op": "shutdown"})
    t_stop = time.monotonic() + 10.0
    for rep in replicas.values():
        if rep.proc is None:
            continue
        try:
            rep.proc.wait(max(0.1, t_stop - time.monotonic()))
        except subprocess.TimeoutExpired:
            rep.kill()
    # Merge front + replica metrics into ONE stream (run_health /
    # critical_path / the stitcher read the whole fleet in one file).
    merged = os.path.join(out_dir, "fleet.metrics.jsonl")
    with open(merged, "w", encoding="utf-8") as out_fh:
        for path in [writer.path] + [r.metrics for r in replicas.values()]:
            if not os.path.exists(path):
                continue
            for row in export_mod.jsonl_read(path):
                out_fh.write(json.dumps(row) + "\n")

    results = {
        rid: {
            "status": t.status,
            **({"reason": t.reason} if t.reason else {}),
            **({"digest": t.result} if t.result is not None else {}),
        }
        for rid, t in sorted(front.tickets.items())
    }
    if args.results:
        with open(args.results, "w") as fh:
            json.dump(results, fh, indent=1)

    summary = {
        "replicas": args.replicas,
        "chaos": plan.to_spec(),
        "wall_s": round(time.monotonic() - t0, 3),
        **front.stats(),
        "health": {str(r): supervisor.state(r)
                   for r in sorted(supervisor.replicas)},
        "unresolved": front.unresolved(),
        "metrics": merged,
        "ok": rc == 0 and not front.unresolved(),
    }
    if args.trace:
        rows = trace_lib.trace_rows(export_mod.read_events(merged))
        trace_lib.write_chrome_trace(args.trace, trace_lib.stitch(rows))
        cp = trace_lib.critical_path(rows)
        summary["trace"] = {
            "path": args.trace, "spans": len(rows),
            "tracks": sorted({r.get("track") for r in rows}),
            "critical_path_p99": {
                seg: round(st["p99"], 4)
                for seg, st in cp["per_segment"].items()
            },
        }
    return summary, (0 if summary["ok"] else max(rc, 1))


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one replica.
    ap.add_argument("--replica-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--families", default="cadmm4")
    ap.add_argument("--buckets", default="4,8")
    ap.add_argument("--capacity", type=int, default=1024)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--poisson-rate", type=float, default=0.0,
                    help="mean arrivals/s (0 = submit everything up "
                         "front)")
    ap.add_argument("--tenants", default="",
                    help="per-tenant policy spec: 'name:rate=R,burst=B,"
                         "weight=W,priority=P;name2:...' (empty = one "
                         "unlimited default tenant)")
    ap.add_argument("--chaos", default="",
                    help="fault plan: 'sigkill@1.5:r0,wedge@2:r1=3' or "
                         "'seeded:<seed>' (also via "
                         f"{fleet_mod.FLEET_FAULTS_ENV})")
    ap.add_argument("--chaos-span", type=float, default=4.0,
                    help="seeded plans: spread faults over this many "
                         "storm-seconds")
    ap.add_argument("--lease", type=float, default=1.0,
                    help="heartbeat lease seconds (suspect at 2 missed, "
                         "down at 5)")
    ap.add_argument("--boot-grace", type=float, default=120.0,
                    help="seconds a replica may take to first heartbeat "
                         "(jax boot on a loaded host)")
    ap.add_argument("--quarantine-after", type=int, default=3,
                    help="restart cycles before a poison replica is "
                         "quarantined")
    ap.add_argument("--bundle", default="")
    ap.add_argument("--require-bundle", action="store_true")
    ap.add_argument("--out-dir", default="artifacts/fleet-local")
    ap.add_argument("--results", default="",
                    help="write per-request {id: {status, digest}} JSON")
    ap.add_argument("--trace", default="",
                    help="write a stitched cross-replica Chrome/Perfetto "
                         "trace (front + r{i} tracks on one clock)")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--poll", type=float, default=0.05,
                    help="front loop poll interval")
    ap.add_argument("--force-multi", action="store_true",
                    help="run multiple replicas even on a 1-core host "
                         "(slow but sound: independent processes, "
                         "generous leases)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.worker:
        return run_worker(args)
    if ((os.cpu_count() or 1) < 2 and args.replicas > 1
            and not args.force_multi):
        # The written skip reason the ci gate keeps: N replica servers
        # time-slicing ONE core stretch every heartbeat lease and make
        # the supervisor's timing assertions meaningless.
        print(json.dumps({
            "skipped": f"1-core host (os.cpu_count()={os.cpu_count()}): "
                       f"cannot run {args.replicas} fleet replicas "
                       "reliably (--force-multi overrides)",
        }), flush=True)
        return 0
    summary, rc = run_fleet(args)
    print(json.dumps(summary), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
