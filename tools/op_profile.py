"""Op-level attribution table from a ``jax.profiler.trace`` capture.

Parses the raw ``*.xplane.pb`` written by ``bench.py --profile DIR`` (the
SURVEY.md §5.1 tracing tier) without TensorBoard: aggregates XLA op event
durations per op name from the device planes and prints a markdown table of
the top-k ops by total self time. The tensorboard profile plugin's converter
is broken against this image's TF build, so this reads the xplane proto
directly (``tensorflow.tsl.profiler.protobuf.xplane_pb2``).

Usage:
  python bench.py --profile /tmp/trace
  python tools/op_profile.py /tmp/trace --top 30 [--json artifacts/op_profile.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from collections import defaultdict


def load_xplanes(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as fh:
            xs.ParseFromString(fh.read())
        spaces.append(xs)
    return spaces


def device_op_times(spaces) -> dict[str, dict]:
    """name -> {total_us, count} aggregated over device-plane XLA op events.

    Device planes are named like '/device:TPU:0'; each line's events carry
    duration_ps and an event-metadata name (the XLA op / fusion name)."""
    agg = defaultdict(lambda: {"total_us": 0.0, "count": 0})
    for xs in spaces:
        for plane in xs.planes:
            # Compute planes: '/device:TPU:0' on accelerator captures,
            # '/host:CPU' on host-only captures (metadata/task planes skipped).
            is_compute = ("device:" in plane.name or "TPU" in plane.name
                          or plane.name == "/host:CPU")
            if not is_compute:
                continue
            meta = plane.event_metadata
            # Prefer XLA-op lines (non-overlapping op events): 'XLA Ops' on
            # TPU device planes, 'xla-cpu-codegen' on host captures. The
            # 'python' line holds nested host frames that would double-count.
            lines = [l for l in plane.lines
                     if "XLA Ops" in l.name or "xla" in l.name.lower()]
            if not lines:
                lines = [l for l in plane.lines if l.name != "python"]
            for line in lines:
                for ev in line.events:
                    name = meta[ev.metadata_id].name if ev.metadata_id in meta \
                        else f"id{ev.metadata_id}"
                    agg[name]["total_us"] += ev.duration_ps / 1e6
                    agg[name]["count"] += 1
    return dict(agg)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--json", default=None, metavar="PATH")
    args = ap.parse_args()

    agg = device_op_times(load_xplanes(args.trace_dir))
    if not agg:
        raise SystemExit("no device-plane op events found in the trace")
    total = sum(v["total_us"] for v in agg.values())
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total_us"])[: args.top]

    print(f"# device op self-time, top {args.top} of {len(agg)} ops "
          f"({total / 1e3:.2f} ms total on-device)")
    print("| op | total ms | calls | % of device time |")
    print("|---|---|---|---|")
    table = []
    for name, v in rows:
        pct = 100.0 * v["total_us"] / total
        short = name if len(name) <= 90 else name[:87] + "..."
        print(f"| `{short}` | {v['total_us'] / 1e3:.3f} | {v['count']} "
              f"| {pct:.1f} |")
        table.append({"op": name, "total_ms": v["total_us"] / 1e3,
                      "calls": v["count"], "pct_device_time": pct})

    if args.json:
        os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
        with open(args.json, "w") as fh:
            json.dump({"device_total_ms": total / 1e3, "top_ops": table}, fh,
                      indent=1)
        print(f"written to {args.json}")


if __name__ == "__main__":
    main()
