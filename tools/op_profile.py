"""Op-level and phase-level attribution from a ``jax.profiler.trace`` capture.

Parses the raw ``*.xplane.pb`` written by ``bench.py --profile DIR`` (the
SURVEY.md §5.1 tracing tier) without TensorBoard: aggregates XLA op event
durations per op name from the device planes and prints a markdown table of
the top-k ops by total self time. The tensorboard profile plugin's converter
is broken against this image's TF build, so this reads the xplane proto
directly (``tensorflow.tsl.profiler.protobuf.xplane_pb2``).

``--by-phase`` rolls op self-time up to the ``jax.named_scope``
annotations over the algorithm phases (``tat.<phase>``, the
``tpu_aerial_transport.obs.phases`` vocabulary) — "what fraction of a
control step is consensus vs. solve" instead of fusion names. Two
attribution sources, in precedence order:

1. a ``tf_op``/``op_name`` stat on the trace event itself (TPU device
   planes record the framework op path per op event);
2. the compiled HLO text dumped next to the trace (``bench.py --profile``
   writes ``<dir>/headline.hlo.txt``): each instruction's
   ``metadata={op_name="..."}`` carries the scope path; trace event names
   are HLO instruction names (modulo ``.clone``/renumber suffixes), so op
   events resolve through the instruction table.

An op's phase is the INNERMOST ``tat.*`` segment of its scope path.
C++ framework events (names containing ``::``) are excluded from the op
self-time base; real XLA ops that resolve to no phase (loop bookkeeping,
copies) report as ``(unattributed)``.

Usage:
  python bench.py --profile /tmp/trace
  python tools/op_profile.py /tmp/trace --top 30 [--json out.json]
  python tools/op_profile.py /tmp/trace --by-phase [--hlo trace/headline.hlo.txt]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
from collections import defaultdict

PHASE_RE = re.compile(r"tat\.([A-Za-z0-9_]+)")
# HLO text: `%name = type op(...), ..., metadata={... op_name="..." ...}`.
_HLO_INSTR_RE = re.compile(
    r"%([\w.\-]+)\s*=\s*.*?op_name=\"([^\"]+)\""
)
# Event-stat keys carrying a framework op path.
_SCOPE_STAT_KEYS = ("tf_op", "op_name")
_SUFFIX_RE = re.compile(r"((\.\d+)|(\.clone)|(\.remat\d*))+$")


def load_xplanes(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as fh:
            xs.ParseFromString(fh.read())
        spaces.append(xs)
    return spaces


def _event_scope(plane, ev) -> str | None:
    """Framework op path recorded ON the event (TPU 'XLA Ops' lines carry a
    tf_op stat; CPU captures usually do not)."""
    for stat in ev.stats:
        meta = plane.stat_metadata.get(stat.metadata_id)
        if meta is None or meta.name not in _SCOPE_STAT_KEYS:
            continue
        if stat.str_value:
            return stat.str_value
        ref = plane.stat_metadata.get(stat.ref_value)
        if ref is not None and ref.name:
            return ref.name
    return None


def op_aggregate(spaces) -> dict[str, dict]:
    """name -> {total_us, count, scope} aggregated over compute-plane XLA
    op events. Device planes are named like '/device:TPU:0'; host-only
    captures put the XLA thunk lines on '/host:CPU'."""
    agg: dict[str, dict] = defaultdict(
        lambda: {"total_us": 0.0, "count": 0, "scope": None}
    )
    for xs in spaces:
        for plane in xs.planes:
            is_compute = ("device:" in plane.name or "TPU" in plane.name
                          or plane.name == "/host:CPU")
            if not is_compute:
                continue
            meta = plane.event_metadata
            # Prefer XLA-op lines (non-overlapping op events): 'XLA Ops' on
            # TPU device planes, 'XLAEigen'/'xla-cpu' thunk lines on host
            # captures. The 'python' line holds nested host frames, the
            # TfrtCpuClient line holds whole-execution framework events
            # (PjitFunction, Execute), and TPU planes also carry an
            # 'XLA Modules' line whose single event SPANS the whole
            # executable — any of these would double-count op time.
            lines = [l for l in plane.lines
                     if "XLA Ops" in l.name or "XLAEigen" in l.name
                     or (l.name.lower().startswith("xla")
                         and "module" not in l.name.lower())]
            if not lines:
                lines = [l for l in plane.lines
                         if "xla" in l.name.lower()
                         and "module" not in l.name.lower()]
            if not lines:
                lines = [l for l in plane.lines if l.name != "python"]
            for line in lines:
                for ev in line.events:
                    name = meta[ev.metadata_id].name if ev.metadata_id \
                        in meta else f"id{ev.metadata_id}"
                    a = agg[name]
                    a["total_us"] += ev.duration_ps / 1e6
                    a["count"] += 1
                    if a["scope"] is None:
                        a["scope"] = _event_scope(plane, ev)
    return dict(agg)


def device_op_times(spaces) -> dict[str, dict]:
    """Back-compat shim for the original per-op table: name ->
    {total_us, count}."""
    return {
        k: {"total_us": v["total_us"], "count": v["count"]}
        for k, v in op_aggregate(spaces).items()
    }


_HLO_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=")
_HLO_REF_RE = re.compile(r"%([\w.\-]+)")


def load_hlo_map(path: str) -> dict[str, str]:
    """instruction name -> op_name metadata, over every instruction (fused
    computations included — a fusion event resolves through either its own
    metadata or its fused instructions' shared phase).

    Compiler-synthesized instructions carry NO metadata (e.g. the
    partial-reduction ``reduce-window`` XLA:CPU splits out of a scoped
    ``reduce``); they inherit the op_name of their first CONSUMER that has
    one — the split piece feeds the instruction it was split from, so the
    consumer's scope is the original op's scope."""
    defs: list[tuple[str, str | None]] = []  # (name, op_name|None).
    consumer_of: dict[str, str] = {}  # operand name -> first consumer name.
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            d = _HLO_DEF_RE.match(line)
            if not d:
                continue
            name = d.group(1)
            m = _HLO_INSTR_RE.search(line)
            defs.append((name, m.group(2) if m else None))
            for ref in _HLO_REF_RE.findall(line)[1:]:
                consumer_of.setdefault(ref, name)
    out = {name: opname for name, opname in defs if opname is not None}
    # Consumer-chain inheritance for metadata-less instructions (depth-
    # limited: split chains are short).
    for name, opname in defs:
        if opname is not None:
            continue
        cur, seen = name, set()
        for _ in range(4):
            cur = consumer_of.get(cur)
            if cur is None or cur in seen:
                break
            seen.add(cur)
            if cur in out:
                out[name] = out[cur]
                break
    return out


def find_hlo_dump(trace_dir: str) -> str | None:
    """The HLO text ``bench.py --profile`` drops next to the trace."""
    hits = sorted(glob.glob(os.path.join(trace_dir, "**", "*.hlo.txt"),
                            recursive=True))
    return hits[0] if hits else None


def _base_name(name: str) -> str:
    return _SUFFIX_RE.sub("", name)


def phase_of(scope_path: str | None) -> str | None:
    """Innermost ``tat.*`` segment of a scope path (nested scopes: the
    finest-grained annotation wins)."""
    if not scope_path:
        return None
    hits = PHASE_RE.findall(scope_path)
    return hits[-1] if hits else None


def rollup_phases(agg: dict[str, dict], hlo_map: dict[str, str] | None):
    """Roll op self-time up to phases.

    Returns ``(rows, op_total_us, attributed_us)`` where ``rows`` maps
    phase -> {total_us, count, ops (example op names)}. C++ framework
    events (``::`` in the name) are excluded from the op-time base;
    everything else counts, attributed or not.
    """
    hlo_map = hlo_map or {}
    # Base-name index: unique-phase fallback for renumbered clones
    # ('sine.4.clone' event vs '%sine.0.clone' instruction).
    base_phases: dict[str, set] = defaultdict(set)
    for iname, opname in hlo_map.items():
        base_phases[_base_name(iname)].add(phase_of(opname))

    rows: dict[str, dict] = defaultdict(
        lambda: {"total_us": 0.0, "count": 0, "ops": []}
    )
    op_total = 0.0
    attributed = 0.0
    for name, a in agg.items():
        if "::" in name or name.startswith(
            ("ThreadpoolListener", "ThunkExecutor", "TfrtCpu",
             "PjitFunction", "ParseArguments")
        ):
            continue  # C++ framework helper, not an XLA op.
        op_total += a["total_us"]
        scope = a["scope"]
        if scope is None:
            scope = hlo_map.get(name) or hlo_map.get(_base_name(name))
        phase = phase_of(scope)
        if phase is None and hlo_map:
            cands = base_phases.get(_base_name(name), set()) - {None}
            if len(cands) == 1:
                phase = next(iter(cands))
        key = phase if phase is not None else "(unattributed)"
        row = rows[key]
        row["total_us"] += a["total_us"]
        row["count"] += a["count"]
        if len(row["ops"]) < 4:
            row["ops"].append(name)
        if phase is not None:
            attributed += a["total_us"]
    return dict(rows), op_total, attributed


def print_phase_table(rows, op_total, attributed) -> list[dict]:
    print(f"# phase-level device self-time "
          f"({op_total / 1e3:.2f} ms of XLA ops; "
          f"{100.0 * attributed / op_total if op_total else 0.0:.1f}% "
          "attributed to tat.* phases)")
    print("| phase | total ms | % of op time | example ops |")
    print("|---|---|---|---|")
    table = []
    for phase, r in sorted(rows.items(), key=lambda kv: -kv[1]["total_us"]):
        pct = 100.0 * r["total_us"] / op_total if op_total else 0.0
        ops = ", ".join(f"`{o}`" for o in r["ops"][:3])
        print(f"| {phase} | {r['total_us'] / 1e3:.3f} | {pct:.1f} | {ops} |")
        table.append({"phase": phase, "total_ms": r["total_us"] / 1e3,
                      "pct_op_time": pct, "calls": r["count"]})
    return table


def print_op_table(agg, top: int) -> list[dict]:
    total = sum(v["total_us"] for v in agg.values())
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total_us"])[:top]
    print(f"# device op self-time, top {top} of {len(agg)} ops "
          f"({total / 1e3:.2f} ms total on-device)")
    print("| op | total ms | calls | % of device time |")
    print("|---|---|---|---|")
    table = []
    for name, v in rows:
        pct = 100.0 * v["total_us"] / total if total else 0.0
        short = name if len(name) <= 90 else name[:87] + "..."
        print(f"| `{short}` | {v['total_us'] / 1e3:.3f} | {v['count']} "
              f"| {pct:.1f} |")
        table.append({"op": name, "total_ms": v["total_us"] / 1e3,
                      "calls": v["count"], "pct_device_time": pct})
    return table


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--json", default=None, metavar="PATH")
    ap.add_argument("--by-phase", action="store_true",
                    help="roll op self-time up to the tat.* named-scope "
                         "phases (obs/phases.py)")
    ap.add_argument("--hlo", default=None, metavar="PATH",
                    help="compiled HLO text for instruction->scope mapping "
                         "(default: *.hlo.txt found under the trace dir)")
    args = ap.parse_args()

    agg = op_aggregate(load_xplanes(args.trace_dir))
    if not agg:
        raise SystemExit("no device-plane op events found in the trace")

    payload: dict = {}
    if args.by_phase:
        hlo_path = args.hlo or find_hlo_dump(args.trace_dir)
        hlo_map = load_hlo_map(hlo_path) if hlo_path else None
        if hlo_map is None:
            print("# note: no HLO dump found — attribution relies on "
                  "per-event tf_op stats only (TPU traces); rerun "
                  "bench.py --profile to get <dir>/headline.hlo.txt")
        rows, op_total, attributed = rollup_phases(agg, hlo_map)
        payload["phases"] = print_phase_table(rows, op_total, attributed)
        payload["op_total_ms"] = op_total / 1e3
        payload["attributed_frac"] = (
            attributed / op_total if op_total else 0.0
        )
    else:
        payload["top_ops"] = print_op_table(agg, args.top)
        payload["device_total_ms"] = (
            sum(v["total_us"] for v in agg.values()) / 1e3
        )

    if args.json:
        os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
        with open(args.json, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"written to {args.json}")


if __name__ == "__main__":
    main()
