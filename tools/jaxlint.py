#!/usr/bin/env python3
"""jaxlint CLI — jit-safety / trace-contract analyzer for the package.

Usage:
    python tools/jaxlint.py [paths...]           # Tier A (pure AST, no jax)
    python tools/jaxlint.py --list-rules
    python tools/jaxlint.py --format json tpu_aerial_transport/
    python tools/jaxlint.py --disable JL003,JL011 path/to/file.py
    python tools/jaxlint.py --contracts          # + Tier B (imports jax)
    python tools/jaxlint.py --host               # Tier C hostlint (HL rules)

Exit status: 0 clean, 1 error-severity findings (warnings too with
--strict-warn), 2 if --assert-no-jax tripped.

Tier A is loaded by FILE PATH (not via the package) so running the lint
never imports jax or initializes a backend — safe on CI boxes with no
accelerator stack; tests/test_jaxlint.py asserts this with
--assert-no-jax. Tier B (--contracts) imports the package normally.
"""

import importlib.util
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ANALYSIS = os.path.join(
    os.path.dirname(_HERE), "tpu_aerial_transport", "analysis"
)


def _load_by_path(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ANALYSIS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    # Sibling-import order matters: rules/entrypoints/host modules first
    # so linter's path-loaded fallback imports resolve to these exact
    # modules.
    _load_by_path("rules")
    _load_by_path("entrypoints")
    _load_by_path("hostflow")
    _load_by_path("knobs")
    _load_by_path("hostrules")
    linter = _load_by_path("linter")
    return linter.main(argv)


if __name__ == "__main__":
    sys.exit(main())
