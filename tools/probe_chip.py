"""Probe TPU-backend liveness under a watchdog and append a timestamped
attempt record to ``artifacts/tpu_probe_log_r5.txt``.

VERDICT r4 item 1: when the chip is wedged, the round must carry an explicit
timestamped attempt log instead of a silent absence of numbers. Exit 0 iff
the accelerator responded (platform != cpu).

The probe body is ``resilience.backend.probe_subprocess`` (loaded by FILE
PATH — this tool must work on hosts where importing jax is the hazard):
subprocess-isolated cold backend init warming a REAL device computation,
matmul + ``convert_element_type``, so a probe "pass" implies the first
real dispatch cannot raise the lazy-init ``UNAVAILABLE`` that ate round 2
(BENCH_r02.json).
"""

from __future__ import annotations

import datetime
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "artifacts", "tpu_probe_log_r5.txt")

_BACKEND_PY = os.path.join(
    REPO, "tpu_aerial_transport", "resilience", "backend.py"
)


def _backend_mod():
    """Load resilience/backend.py WITHOUT importing the package (which
    would import jax); the module itself has no module-scope jax import."""
    name = "_tat_backend_pathload"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, _BACKEND_PY)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def probe(timeout_s: int = 60) -> tuple[bool, str]:
    ok, detail = _backend_mod().probe_subprocess(timeout_s=timeout_s)
    if ok and detail == "cpu":
        return False, "silent CPU fallback (platform=cpu)"
    return ok, detail


def main() -> int:
    ok, detail = probe()
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M:%S UTC"
    )
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as fh:
        fh.write(f"{stamp}  {'ALIVE' if ok else 'DOWN'}  {detail}\n")
    print(f"{stamp}  {'ALIVE' if ok else 'DOWN'}  {detail}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
