"""Probe TPU-backend liveness under a watchdog and append a timestamped
attempt record to ``artifacts/tpu_probe_log_r5.txt``.

VERDICT r4 item 1: when the chip is wedged, the round must carry an explicit
timestamped attempt log instead of a silent absence of numbers. Exit 0 iff
the accelerator responded (platform != cpu).
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "artifacts", "tpu_probe_log_r5.txt")

PROBE_CODE = (
    "import os, jax\n"
    "envp = os.environ.get('JAX_PLATFORMS')\n"
    "if envp: jax.config.update('jax_platforms', envp)\n"
    "d = jax.devices()\n"
    "import jax.numpy as jnp\n"
    "x = jnp.ones((128, 128)); s = float((x @ x).sum())\n"
    "print('BACKEND_OK', d[0].platform, len(d), s)"
)


def probe(timeout_s: int = 60) -> tuple[bool, str]:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            capture_output=True, text=True, timeout=timeout_s,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        return False, f"timeout after {timeout_s}s (chip unreachable/wedged)"
    out = proc.stdout.strip().splitlines()
    ok_line = next((l for l in out if l.startswith("BACKEND_OK")), None)
    if proc.returncode != 0 or ok_line is None:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-1:]
        return False, f"probe rc={proc.returncode}: {' '.join(tail)[:200]}"
    platform = ok_line.split()[1]
    if platform == "cpu":
        return False, f"silent CPU fallback ({ok_line})"
    return True, ok_line


def main() -> int:
    ok, detail = probe()
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M:%S UTC"
    )
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as fh:
        fh.write(f"{stamp}  {'ALIVE' if ok else 'DOWN'}  {detail}\n")
    print(f"{stamp}  {'ALIVE' if ok else 'DOWN'}  {detail}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
