"""Probe TPU-backend liveness under a watchdog and append a timestamped
attempt record to ``artifacts/tpu_probe_log_r5.txt``.

VERDICT r4 item 1: when the chip is wedged, the round must carry an explicit
timestamped attempt log instead of a silent absence of numbers. Exit 0 iff
the accelerator responded (platform != cpu).

The probe body is ``resilience.backend.probe_subprocess`` (loaded by FILE
PATH — this tool must work on hosts where importing jax is the hazard):
subprocess-isolated cold backend init warming a REAL device computation,
matmul + ``convert_element_type``, so a probe "pass" implies the first
real dispatch cannot raise the lazy-init ``UNAVAILABLE`` that ate round 2
(BENCH_r02.json).
"""

from __future__ import annotations

import datetime
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "artifacts", "tpu_probe_log_r5.txt")

_BACKEND_PY = os.path.join(
    REPO, "tpu_aerial_transport", "resilience", "backend.py"
)


def _backend_mod():
    """Load resilience/backend.py WITHOUT importing the package (which
    would import jax); the module itself has no module-scope jax import."""
    name = "_tat_backend_pathload"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, _BACKEND_PY)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def probe(timeout_s: int = 60, bundle_dir: str | None = None,
          notes: list | None = None) -> tuple[bool, str]:
    """``bundle_dir`` (or the ``TAT_AOT_BUNDLE_DIR`` env var) makes the
    probed dispatch replay the AOT bundle's PRECOMPILED probe executable
    instead of compiling one — a cold probe can no longer burn its
    deadline inside XLA. A stale/corrupt bundle downgrades to the compile
    probe and surfaces through ``notes`` (a ``bundle_stale`` rebuild hint,
    never a chip indictment)."""
    ok, detail = _backend_mod().probe_subprocess(
        timeout_s=timeout_s, bundle_dir=bundle_dir, notes=notes
    )
    if ok and detail == "cpu":
        return False, "silent CPU fallback (platform=cpu)"
    return ok, detail


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=int, default=60)
    ap.add_argument("--bundle-dir", default=None,
                    help="AOT bundle whose precompiled probe executable "
                         "the probe prefers (default: TAT_AOT_BUNDLE_DIR)")
    args = ap.parse_args(argv)
    notes: list = []
    ok, detail = probe(timeout_s=args.timeout, bundle_dir=args.bundle_dir,
                       notes=notes)
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M:%S UTC"
    )
    note_s = ("  " + " ".join(notes)) if notes else ""
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as fh:
        fh.write(f"{stamp}  {'ALIVE' if ok else 'DOWN'}  {detail}{note_s}\n")
    print(f"{stamp}  {'ALIVE' if ok else 'DOWN'}  {detail}{note_s}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
