#!/bin/bash
# TPU-return watcher (round 5): probe the chip every 10 min; on the first
# ALIVE, run the full measurement sequence ONCE (smoke -> headline -> sweep
# --resume), logging everything to artifacts/, then exit. The sweep is
# checkpointed (BENCH_SWEEP_PARTIAL.json), so a tunnel death mid-sweep loses
# nothing. Single-instance via pidfile.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
LOG="$REPO/artifacts/tpu_watch.log"
PIDFILE="/tmp/tpu_watch_r5.pid"

if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
    echo "watcher already running (pid $(cat "$PIDFILE"))" >> "$LOG"
    exit 0
fi
echo $$ > "$PIDFILE"
trap 'rm -f "$PIDFILE"' EXIT

log() { echo "$(date -u '+%F %T UTC')  $*" >> "$LOG"; }

log "watcher started (pid $$)"
while true; do
    if python "$REPO/tools/probe_chip.py" >> "$LOG" 2>&1; then
        log "CHIP ALIVE - starting measurement sequence"
        log "=== smoke ==="
        timeout 900 python "$REPO/bench.py" --smoke >> "$LOG" 2>&1
        log "smoke rc=$?"
        log "=== headline ==="
        timeout 1800 python "$REPO/bench.py" > "$REPO/artifacts/headline_r5.json" 2>> "$LOG"
        log "headline rc=$? (artifacts/headline_r5.json)"
        log "=== sweep ==="
        timeout 14400 python "$REPO/bench.py" --sweep --resume >> "$REPO/artifacts/sweep_r5.log" 2>&1
        log "sweep rc=$? (artifacts/sweep_r5.log; BENCH_SWEEP.json on success)"
        log "sequence done - exiting"
        rm -f "$PIDFILE"
        exit 0
    fi
    sleep 600
done
