#!/bin/bash
# TPU-return watcher (round 5): probe the chip every 10 min; on the first
# ALIVE, run the full measurement sequence ONCE (smoke -> headline -> sweep
# --resume), logging everything to artifacts/, then exit. The sweep is
# checkpointed (BENCH_SWEEP_PARTIAL.json), so a tunnel death mid-sweep loses
# nothing. Single-instance via pidfile.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
LOG="$REPO/artifacts/tpu_watch.log"
PIDFILE="/tmp/tpu_watch_r5.pid"

if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
    echo "watcher already running (pid $(cat "$PIDFILE"))" >> "$LOG"
    exit 0
fi
echo $$ > "$PIDFILE"
trap 'rm -f "$PIDFILE"' EXIT

log() { echo "$(date -u '+%F %T UTC')  $*" >> "$LOG"; }

log "watcher started (pid $$)"
HL_DONE=0
TRIES=0
MAX_TRIES=5
while true; do
    if python "$REPO/tools/probe_chip.py" >> "$LOG" 2>&1; then
        TRIES=$((TRIES + 1))
        log "CHIP ALIVE - starting measurement sequence (attempt $TRIES/$MAX_TRIES)"
        log "=== smoke ==="
        timeout 900 python "$REPO/bench.py" --smoke >> "$LOG" 2>&1
        log "smoke rc=$?"
        if [ "$HL_DONE" -eq 0 ]; then
            log "=== headline ==="
            # Temp file + mv on success: a retry that wedges must not
            # truncate an already-captured headline deliverable.
            timeout 1800 python "$REPO/bench.py" > "$REPO/artifacts/headline_r5.json.tmp" 2>> "$LOG"
            hl_rc=$?
            if [ "$hl_rc" -eq 0 ]; then
                mv "$REPO/artifacts/headline_r5.json.tmp" "$REPO/artifacts/headline_r5.json"
                HL_DONE=1
            fi
            log "headline rc=$hl_rc (artifacts/headline_r5.json)"
        else
            log "headline already captured - skipping"
        fi
        log "=== sweep ==="
        timeout 14400 python "$REPO/bench.py" --sweep --resume >> "$REPO/artifacts/sweep_r5.log" 2>&1
        sw_rc=$?
        log "sweep rc=$sw_rc (artifacts/sweep_r5.log; BENCH_SWEEP.json on success)"
        # Only stand down once BOTH deliverables are in hand; a chip that
        # re-wedged mid-sequence must re-arm the watcher, not end it — the
        # sweep checkpoint makes the retry cheap.
        if [ "$HL_DONE" -eq 1 ] && [ "$sw_rc" -eq 0 ]; then
            log "sequence complete - exiting"
            exit 0
        fi
        # Deterministic failures (e.g. the sweep's refusing-resume guard on
        # a dirty git tree) would loop forever with the chip alive — detect
        # the refusal and cap total attempts, loudly.
        if tail -5 "$REPO/artifacts/sweep_r5.log" | grep -q "refusing --resume"; then
            log "FATAL: sweep refuses --resume (git head mismatch/dirty tree) - operator action needed, exiting"
            exit 2
        fi
        if [ "$TRIES" -ge "$MAX_TRIES" ]; then
            log "FATAL: $MAX_TRIES alive-attempts without a complete sequence - exiting"
            exit 3
        fi
        log "sequence incomplete (HL_DONE=$HL_DONE sweep=$sw_rc) - re-arming"
    fi
    sleep 600
done
