# Root conftest: configure JAX for CPU-hosted multi-device testing BEFORE jax imports.
#
# Tests run on a virtual 8-device CPU mesh so the sharding/collective code paths
# (parallel/) are exercised without TPU hardware, mirroring the strategy described in
# SURVEY.md §4 ("single-process multi-device tests on CPU").
import os

# Force CPU even when the ambient environment selects a TPU platform (e.g.
# JAX_PLATFORMS=axon): the test suite needs 8 virtual devices for the collective
# code paths, and the driver benchmarks on real TPU separately via bench.py.
os.environ["JAX_PLATFORMS"] = "cpu"

# Virtual-device request through the ONE shared knob (utils/platform.py):
# TAT_VIRTUAL_DEVICES overrides the 8-device default; an ambient XLA_FLAGS
# pin wins over both (tests/conftest.py then skips mesh tests with an
# actionable message). platform.py imports no jax — safe pre-init.
from tpu_aerial_transport.utils.platform import apply_virtual_devices  # noqa: E402

apply_virtual_devices(default=8)

# The axon site hook (PYTHONPATH=/root/.axon_site) rewrites jax_platforms to
# "axon,cpu" at import, overriding the env var — override it back at the config
# level, which wins because backends initialize lazily on first use.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (works on CPU since jax 0.4.30s): the
# suite is COMPILE-bound — the 8-virtual-device shard_map tests alone cost
# ~7 min of XLA time per cold run — and programs are identical run-to-run,
# so warm re-runs cut tier-1 wall time severalfold. One shared knob
# (utils/platform.py): override with TAT_XLA_CACHE_DIR, disable with
# TAT_XLA_CACHE_DIR=""; bench.py, the bench_retry children, and the AOT
# serve driver route through the same helper.
from tpu_aerial_transport.utils.platform import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
